// ray_tpu C++ client API.
//
// Parity: the reference's C++ worker API surface (cpp/include/ray/api.h —
// ray::Init, ray::Task(...).Remote(), ray::Get, actor handles), re-scoped to
// the cross-language client model: functions/actors are invoked by REGISTERED
// name on the Python session (the descriptor model of cross_language.py), over
// the session's JSON-framed xlang endpoint (ray_tpu/experimental/xlang.py).
// Header-only; no third-party dependencies (a minimal JSON value type and
// recursive-descent parser are included).
//
// Usage:
//   rtpu::Client c = rtpu::Init("127.0.0.1", port, token);
//   rtpu::Json r = c.Task("add").Remote(rtpu::Json(1.0), rtpu::Json(2.0));
//   rtpu::ObjectRef ref = c.Put(rtpu::Json("hello"));
//   rtpu::Json v = c.Get(ref);
//   rtpu::Actor a = c.Actor("Counter").Remote();
//   a.Call("inc");

#pragma once

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cmath>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <type_traits>
#include <string>
#include <vector>

namespace rtpu {

// ----------------------------------------------------------------- JSON
struct Json {
  enum Type { Null, Bool, Num, Str, Arr, Obj } type = Null;
  bool b = false;
  double num = 0;
  std::string str;
  std::vector<Json> arr;
  std::map<std::string, Json> obj;

  Json() {}
  Json(bool v) : type(Bool), b(v) {}
  Json(double v) : type(Num), num(v) {}
  Json(int v) : type(Num), num(v) {}
  Json(long v) : type(Num), num(static_cast<double>(v)) {}
  Json(const char* v) : type(Str), str(v) {}
  Json(const std::string& v) : type(Str), str(v) {}
  static Json Array(std::vector<Json> items) {
    Json j; j.type = Arr; j.arr = std::move(items); return j;
  }
  static Json Object() { Json j; j.type = Obj; return j; }

  bool is_null() const { return type == Null; }
  double AsNum() const {
    if (type != Num) throw std::runtime_error("json: not a number");
    return num;
  }
  long AsInt() const { return static_cast<long>(AsNum()); }
  const std::string& AsStr() const {
    if (type != Str) throw std::runtime_error("json: not a string");
    return str;
  }
  const Json& operator[](const std::string& k) const {
    static Json null_;
    auto it = obj.find(k);
    return it == obj.end() ? null_ : it->second;
  }

  void Dump(std::ostringstream& o) const {
    switch (type) {
      case Null: o << "null"; break;
      case Bool: o << (b ? "true" : "false"); break;
      case Num: {
        if (std::isfinite(num) && num == static_cast<long long>(num) &&
            std::fabs(num) < 9e15) {
          o << static_cast<long long>(num);
        } else {
          o.precision(17);
          o << num;
        }
        break;
      }
      case Str: DumpStr(o, str); break;
      case Arr: {
        o << '[';
        for (size_t i = 0; i < arr.size(); i++) {
          if (i) o << ',';
          arr[i].Dump(o);
        }
        o << ']';
        break;
      }
      case Obj: {
        o << '{';
        bool first = true;
        for (auto& kv : obj) {
          if (!first) o << ',';
          first = false;
          DumpStr(o, kv.first);
          o << ':';
          kv.second.Dump(o);
        }
        o << '}';
        break;
      }
    }
  }
  std::string Dump() const {
    std::ostringstream o;
    Dump(o);
    return o.str();
  }

  static void DumpStr(std::ostringstream& o, const std::string& s) {
    o << '"';
    for (unsigned char c : s) {
      switch (c) {
        case '"': o << "\\\""; break;
        case '\\': o << "\\\\"; break;
        case '\n': o << "\\n"; break;
        case '\r': o << "\\r"; break;
        case '\t': o << "\\t"; break;
        default:
          if (c < 0x20) {
            char buf[8];
            snprintf(buf, sizeof buf, "\\u%04x", c);
            o << buf;
          } else {
            o << c;
          }
      }
    }
    o << '"';
  }
};

// Recursive-descent parser (subset sufficient for the xlang protocol:
// standard JSON with \uXXXX escapes decoded to UTF-8).
class JsonParser {
 public:
  explicit JsonParser(const std::string& s) : s_(s) {}
  Json Parse() {
    Json v = Value();
    Ws();
    if (i_ != s_.size()) throw std::runtime_error("json: trailing data");
    return v;
  }

 private:
  const std::string& s_;
  size_t i_ = 0;

  void Ws() {
    while (i_ < s_.size() && (s_[i_] == ' ' || s_[i_] == '\t' ||
                              s_[i_] == '\n' || s_[i_] == '\r'))
      i_++;
  }
  char Peek() {
    if (i_ >= s_.size()) throw std::runtime_error("json: eof");
    return s_[i_];
  }
  void Expect(char c) {
    if (Peek() != c) throw std::runtime_error(std::string("json: expected ") + c);
    i_++;
  }
  bool Lit(const char* lit) {
    size_t n = strlen(lit);
    if (s_.compare(i_, n, lit) == 0) {
      i_ += n;
      return true;
    }
    return false;
  }
  Json Value() {
    Ws();
    char c = Peek();
    if (c == '{') return ObjectV();
    if (c == '[') return ArrayV();
    if (c == '"') {
      Json j;
      j.type = Json::Str;
      j.str = StringV();
      return j;
    }
    if (Lit("true")) return Json(true);
    if (Lit("false")) return Json(false);
    if (Lit("null")) return Json();
    return NumberV();
  }
  Json ObjectV() {
    Expect('{');
    Json j = Json::Object();
    Ws();
    if (Peek() == '}') {
      i_++;
      return j;
    }
    while (true) {
      Ws();
      std::string k = StringV();
      Ws();
      Expect(':');
      j.obj[k] = Value();
      Ws();
      if (Peek() == ',') {
        i_++;
        continue;
      }
      Expect('}');
      return j;
    }
  }
  Json ArrayV() {
    Expect('[');
    Json j;
    j.type = Json::Arr;
    Ws();
    if (Peek() == ']') {
      i_++;
      return j;
    }
    while (true) {
      j.arr.push_back(Value());
      Ws();
      if (Peek() == ',') {
        i_++;
        continue;
      }
      Expect(']');
      return j;
    }
  }
  std::string StringV() {
    Expect('"');
    std::string out;
    while (true) {
      char c = Peek();
      i_++;
      if (c == '"') return out;
      if (c == '\\') {
        char e = Peek();
        i_++;
        switch (e) {
          case 'n': out += '\n'; break;
          case 't': out += '\t'; break;
          case 'r': out += '\r'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'u': {
            unsigned cp = std::stoul(s_.substr(i_, 4), nullptr, 16);
            i_ += 4;
            // BMP-only escape decoding (enough for the protocol's ASCII use)
            if (cp < 0x80) {
              out += static_cast<char>(cp);
            } else if (cp < 0x800) {
              out += static_cast<char>(0xC0 | (cp >> 6));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            } else {
              out += static_cast<char>(0xE0 | (cp >> 12));
              out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            }
            break;
          }
          default: out += e;
        }
      } else {
        out += c;
      }
    }
  }
  Json NumberV() {
    size_t start = i_;
    while (i_ < s_.size() &&
           (isdigit(static_cast<unsigned char>(s_[i_])) || s_[i_] == '-' ||
            s_[i_] == '+' || s_[i_] == '.' || s_[i_] == 'e' || s_[i_] == 'E'))
      i_++;
    return Json(std::stod(s_.substr(start, i_ - start)));
  }
};

// -------------------------------------------------------- typed conversions
// The typed task API (reference: cpp/include/ray/api.h — ray::Task(fn)
// .Remote(native args) with typed ObjectRef<T> returns): native C++ values
// convert to/from the wire Json automatically, so call sites never touch
// Json when they don't want to.
inline Json ToJson(const Json& v) { return v; }
inline Json ToJson(bool v) { return Json(v); }
inline Json ToJson(const char* v) { return Json(v); }
inline Json ToJson(const std::string& v) { return Json(v); }
template <typename T,
          typename std::enable_if<std::is_arithmetic<T>::value, int>::type = 0>
Json ToJson(T v) { return Json(static_cast<double>(v)); }
template <typename T>
Json ToJson(const std::map<std::string, T>& v);  // fwd: vector<map<...>> args
template <typename T>
Json ToJson(const std::vector<T>& v) {
  std::vector<Json> items;
  items.reserve(v.size());
  for (const auto& x : v) items.push_back(ToJson(x));
  return Json::Array(std::move(items));
}
template <typename T>
Json ToJson(const std::map<std::string, T>& v) {
  Json o = Json::Object();
  for (const auto& kv : v) o.obj[kv.first] = ToJson(kv.second);
  return o;
}

template <typename T>
struct FromJsonImpl;
template <> struct FromJsonImpl<Json> {
  static Json Get(const Json& j) { return j; }
};
template <> struct FromJsonImpl<double> {
  static double Get(const Json& j) { return j.AsNum(); }
};
template <> struct FromJsonImpl<long> {
  static long Get(const Json& j) { return j.AsInt(); }
};
template <> struct FromJsonImpl<int> {
  static int Get(const Json& j) { return static_cast<int>(j.AsInt()); }
};
template <> struct FromJsonImpl<bool> {
  static bool Get(const Json& j) {
    if (j.type != Json::Bool) throw std::runtime_error("json: not a bool");
    return j.b;
  }
};
template <> struct FromJsonImpl<std::string> {
  static std::string Get(const Json& j) { return j.AsStr(); }
};
template <typename T> struct FromJsonImpl<std::vector<T>> {
  static std::vector<T> Get(const Json& j) {
    if (j.type != Json::Arr) throw std::runtime_error("json: not an array");
    std::vector<T> out;
    out.reserve(j.arr.size());
    for (const auto& x : j.arr) out.push_back(FromJsonImpl<T>::Get(x));
    return out;
  }
};
template <typename T>
T FromJson(const Json& j) { return FromJsonImpl<T>::Get(j); }

// ----------------------------------------------------------------- client
struct ObjectRef {
  std::string id;
};

template <typename T>
struct TypedRef {  // typed ObjectRef (reference: ray::ObjectRef<T>)
  std::string id;
};

class Client;

class TaskCaller {
 public:
  TaskCaller(Client* c, std::string func) : c_(c), func_(std::move(func)) {}
  template <typename... A>
  Json Remote(A&&... args);  // call-and-wait (reference Task().Remote + Get)
  template <typename... A>
  ObjectRef RemoteAsync(A&&... args);  // returns a ref; Get() later

 private:
  Client* c_;
  std::string func_;
};

// Typed task caller: native args in, R out (reference: the templated
// ray::Task(fn).Remote() whose ObjectRef carries the return type).
template <typename R>
class TypedTaskCaller {
 public:
  TypedTaskCaller(Client* c, std::string func)
      : inner_(c, std::move(func)) {}
  template <typename... A>
  R Remote(A&&... args) {
    return FromJson<R>(inner_.Remote(ToJson(std::forward<A>(args))...));
  }
  template <typename... A>
  TypedRef<R> RemoteAsync(A&&... args) {
    return TypedRef<R>{
        inner_.RemoteAsync(ToJson(std::forward<A>(args))...).id};
  }

 private:
  TaskCaller inner_;
};

class Actor {
 public:
  Actor() {}
  Actor(Client* c, std::string id) : c_(c), id_(std::move(id)) {}
  template <typename... A>
  Json Call(const std::string& method, A&&... args);
  void Kill();
  const std::string& Id() const { return id_; }

 private:
  Client* c_ = nullptr;  // Call/Kill on a default-constructed Actor throws
  std::string id_;
};

class Client {
 public:
  Client(const std::string& host, int port, const std::string& token) {
    fd_ = socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) throw std::runtime_error("socket() failed");
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1)
      throw std::runtime_error("bad host: " + host);
    if (connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof addr) != 0)
      throw std::runtime_error("connect failed");
    Json hello = Json::Object();
    hello.obj["op"] = Json("hello");
    hello.obj["token"] = Json(token);
    Request(hello);
  }
  ~Client() {
    if (fd_ >= 0) close(fd_);
  }
  Client(const Client&) = delete;
  Client& operator=(const Client&) = delete;

  TaskCaller Task(const std::string& func) { return TaskCaller(this, func); }

  // Typed variant: rtpu::Json never appears at the call site —
  //   double r = c.TypedTask<double>("add").Remote(3, 4);
  template <typename R>
  TypedTaskCaller<R> TypedTask(const std::string& func) {
    return TypedTaskCaller<R>(this, func);
  }

  Actor ActorCreate(const std::string& cls, std::vector<Json> args = {}) {
    Json m = Json::Object();
    m.obj["op"] = Json("actor_create");
    m.obj["cls"] = Json(cls);
    m.obj["args"] = Json::Array(std::move(args));
    return Actor(this, Request(m)["actor"].AsStr());
  }

  ObjectRef Put(const Json& value) {
    Json m = Json::Object();
    m.obj["op"] = Json("put");
    m.obj["value"] = value;
    return ObjectRef{Request(m)["ref"].AsStr()};
  }

  Json Get(const ObjectRef& ref) {
    Json m = Json::Object();
    m.obj["op"] = Json("get");
    m.obj["ref"] = Json(ref.id);
    return Request(m);
  }

  template <typename T>
  T Get(const TypedRef<T>& ref) {
    return FromJson<T>(Get(ObjectRef{ref.id}));
  }

  // Release the server-held borrow for a Put()/RemoteAsync() ref; without
  // this a long-lived client pins every object for the server's lifetime.
  void Free(const ObjectRef& ref) {
    Json m = Json::Object();
    m.obj["op"] = Json("free");
    m.obj["ref"] = Json(ref.id);
    Request(m);
  }

  template <typename T>
  void Free(const TypedRef<T>& ref) { Free(ObjectRef{ref.id}); }

  std::vector<std::string> ListFuncs() {
    Json m = Json::Object();
    m.obj["op"] = Json("list_funcs");
    Json r = Request(m);
    std::vector<std::string> out;
    for (auto& f : r["funcs"].arr) out.push_back(f.AsStr());
    return out;
  }

  // one in-flight request per client (callers wanting parallelism open
  // multiple clients — connections are cheap)
  Json Request(Json msg) {
    msg.obj["id"] = Json(static_cast<double>(++next_id_));
    std::string body = msg.Dump();
    uint32_t n = htonl(static_cast<uint32_t>(body.size()));
    SendAll(reinterpret_cast<const char*>(&n), 4);
    SendAll(body.data(), body.size());
    char hdr[4];
    RecvAll(hdr, 4);
    uint32_t len;
    memcpy(&len, hdr, 4);
    len = ntohl(len);
    std::string reply(len, '\0');
    RecvAll(&reply[0], len);
    Json r = JsonParser(reply).Parse();
    if (!r["error"].is_null())
      throw std::runtime_error("remote error: " + r["error"].AsStr());
    return r["result"];
  }

 private:
  void SendAll(const char* p, size_t n) {
    while (n) {
      ssize_t k = send(fd_, p, n, 0);
      if (k <= 0) throw std::runtime_error("send failed");
      p += k;
      n -= static_cast<size_t>(k);
    }
  }
  void RecvAll(char* p, size_t n) {
    while (n) {
      ssize_t k = recv(fd_, p, n, 0);
      if (k <= 0) throw std::runtime_error("recv failed (server closed?)");
      p += k;
      n -= static_cast<size_t>(k);
    }
  }
  int fd_ = -1;
  uint64_t next_id_ = 0;
};

template <typename... A>
Json TaskCaller::Remote(A&&... args) {
  Json m = Json::Object();
  m.obj["op"] = Json("call");
  m.obj["func"] = Json(func_);
  m.obj["args"] = Json::Array({Json(std::forward<A>(args))...});
  return c_->Request(m);
}

template <typename... A>
ObjectRef TaskCaller::RemoteAsync(A&&... args) {
  Json m = Json::Object();
  m.obj["op"] = Json("submit");
  m.obj["func"] = Json(func_);
  m.obj["args"] = Json::Array({Json(std::forward<A>(args))...});
  return ObjectRef{c_->Request(m)["ref"].AsStr()};
}

template <typename... A>
Json Actor::Call(const std::string& method, A&&... args) {
  if (c_ == nullptr) throw std::runtime_error("Actor not initialized");
  Json m = Json::Object();
  m.obj["op"] = Json("actor_call");
  m.obj["actor"] = Json(id_);
  m.obj["method"] = Json(method);
  m.obj["args"] = Json::Array({Json(std::forward<A>(args))...});
  return c_->Request(m);
}

inline void Actor::Kill() {
  if (c_ == nullptr) throw std::runtime_error("Actor not initialized");
  Json m = Json::Object();
  m.obj["op"] = Json("kill_actor");
  m.obj["actor"] = Json(id_);
  c_->Request(m);
}

inline Client Init(const std::string& host, int port, const std::string& token) {
  return Client(host, port, token);
}

}  // namespace rtpu
