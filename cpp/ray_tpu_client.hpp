// ray_tpu C++ client API.
//
// Parity: the reference's C++ worker API surface (cpp/include/ray/api.h —
// ray::Init, ray::Task(...).Remote(), ray::Get, actor handles), re-scoped to
// the cross-language client model: functions/actors are invoked by
// REGISTERED name on the Python session (the descriptor model of
// cross_language.py). The client speaks the session's NATIVE control plane
// (ray_tpu/core/rpc/): length-prefixed msgpack frames, hello-time schema
// version negotiation, numbered ops — the same wire Python workers use, not
// a JSON side-channel. Header-only; a minimal msgpack codec is included.
//
// Usage:
//   rtpu::Client c = rtpu::Init("127.0.0.1", port, token);
//   rtpu::Json r = c.Task("add").Remote(rtpu::Json(1.0), rtpu::Json(2.0));
//   rtpu::ObjectRef ref = c.Put(rtpu::Json("hello"));
//   rtpu::Json v = c.Get(ref);
//   rtpu::Actor a = c.Actor("Counter").Remote();
//   a.Call("inc");

#pragma once

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cmath>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <type_traits>
#include <string>
#include <vector>

namespace rtpu {

// Wire protocol constants — MUST match ray_tpu/core/rpc/schema.py +
// codec.py (numbered, append-only schemas; renumbering is a wire break).
constexpr const char* kWireMagic = "rtpu1";
constexpr int kWireVersionMin = 2;  // xl_* ops exist since v2
constexpr int kWireVersionMax = 2;

enum FrameKind { kHello = 0, kRequest = 1, kNotify = 2, kReply = 3,
                 kError = 4, kGoodbye = 5 };

constexpr uint32_t kMaxFrame = 1u << 31;  // codec.py MAX_FRAME

enum OpNum {
  kOpHello = 1,
  kOpXlCall = 41,
  kOpXlSubmit = 42,
  kOpXlGet = 43,
  kOpXlPut = 44,
  kOpXlFree = 45,
  kOpXlActorCreate = 46,
  kOpXlActorCall = 47,
  kOpXlKillActor = 48,
  kOpXlListFuncs = 49,
};

// -------------------------------------------------------------- value type
// Language-neutral value (named Json for API compatibility; the wire is
// msgpack, which adds a native binary type — no base64 envelopes).
struct Json {
  enum Type { Null, Bool, Num, Str, Arr, Obj, Bin } type = Null;
  bool b = false;
  double num = 0;
  std::string str;  // Str text or Bin bytes
  std::vector<Json> arr;
  std::map<std::string, Json> obj;

  Json() {}
  Json(bool v) : type(Bool), b(v) {}
  Json(double v) : type(Num), num(v) {}
  Json(int v) : type(Num), num(v) {}
  Json(long v) : type(Num), num(static_cast<double>(v)) {}
  Json(const char* v) : type(Str), str(v) {}
  Json(const std::string& v) : type(Str), str(v) {}
  static Json Array(std::vector<Json> items) {
    Json j; j.type = Arr; j.arr = std::move(items); return j;
  }
  static Json Object() { Json j; j.type = Obj; return j; }
  static Json Bytes(std::string raw) {
    Json j; j.type = Bin; j.str = std::move(raw); return j;
  }

  bool is_null() const { return type == Null; }
  double AsNum() const {
    if (type != Num) throw std::runtime_error("value: not a number");
    return num;
  }
  long AsInt() const { return static_cast<long>(AsNum()); }
  const std::string& AsStr() const {
    if (type != Str) throw std::runtime_error("value: not a string");
    return str;
  }
  const std::string& AsBytes() const {
    if (type != Bin) throw std::runtime_error("value: not bytes");
    return str;
  }
  const Json& operator[](const std::string& k) const {
    static Json null_;
    auto it = obj.find(k);
    return it == obj.end() ? null_ : it->second;
  }

  // Debug rendering (JSON-ish; bytes shown as <n bytes>).
  void Dump(std::ostringstream& o) const {
    switch (type) {
      case Null: o << "null"; break;
      case Bool: o << (b ? "true" : "false"); break;
      case Num: {
        if (std::isfinite(num) && num == static_cast<long long>(num) &&
            std::fabs(num) < 9e15) {
          o << static_cast<long long>(num);
        } else {
          o.precision(17);
          o << num;
        }
        break;
      }
      case Str: o << '"' << str << '"'; break;
      case Bin: o << '<' << str.size() << " bytes>"; break;
      case Arr: {
        o << '[';
        for (size_t i = 0; i < arr.size(); i++) {
          if (i) o << ',';
          arr[i].Dump(o);
        }
        o << ']';
        break;
      }
      case Obj: {
        o << '{';
        bool first = true;
        for (auto& kv : obj) {
          if (!first) o << ',';
          first = false;
          o << '"' << kv.first << "\":";
          kv.second.Dump(o);
        }
        o << '}';
        break;
      }
    }
  }
  std::string Dump() const {
    std::ostringstream o;
    Dump(o);
    return o.str();
  }
};

// ------------------------------------------------------------ msgpack pack
class MsgpackWriter {
 public:
  std::string out;

  void PackNil() { out += static_cast<char>(0xc0); }
  void PackBool(bool v) { out += static_cast<char>(v ? 0xc3 : 0xc2); }

  void PackInt(int64_t v) {
    if (v >= 0 && v <= 127) {
      out += static_cast<char>(v);
    } else if (v < 0 && v >= -32) {
      out += static_cast<char>(0xe0 | (v + 32));
    } else {
      out += static_cast<char>(0xd3);
      PackBE64(static_cast<uint64_t>(v));
    }
  }
  void PackDouble(double v) {
    out += static_cast<char>(0xcb);
    uint64_t bits;
    memcpy(&bits, &v, 8);
    PackBE64(bits);
  }
  void PackStr(const std::string& s) {
    size_t n = s.size();
    if (n <= 31) {
      out += static_cast<char>(0xa0 | n);
    } else if (n <= 0xffff) {
      out += static_cast<char>(0xda);
      PackBE16(static_cast<uint16_t>(n));
    } else {
      out += static_cast<char>(0xdb);
      PackBE32(static_cast<uint32_t>(n));
    }
    out += s;
  }
  void PackBin(const std::string& s) {
    out += static_cast<char>(0xc6);
    PackBE32(static_cast<uint32_t>(s.size()));
    out += s;
  }
  void PackArrayHeader(size_t n) {
    if (n <= 15) {
      out += static_cast<char>(0x90 | n);
    } else {
      out += static_cast<char>(0xdd);
      PackBE32(static_cast<uint32_t>(n));
    }
  }
  void PackMapHeader(size_t n) {
    if (n <= 15) {
      out += static_cast<char>(0x80 | n);
    } else {
      out += static_cast<char>(0xdf);
      PackBE32(static_cast<uint32_t>(n));
    }
  }
  void PackValue(const Json& v) {
    switch (v.type) {
      case Json::Null: PackNil(); break;
      case Json::Bool: PackBool(v.b); break;
      case Json::Num: {
        // integral doubles travel as ints (matches the Python side's
        // int/float distinction for registered functions doing arithmetic)
        if (std::isfinite(v.num) && v.num == static_cast<int64_t>(v.num) &&
            std::fabs(v.num) < 9e15) {
          PackInt(static_cast<int64_t>(v.num));
        } else {
          PackDouble(v.num);
        }
        break;
      }
      case Json::Str: PackStr(v.str); break;
      case Json::Bin: PackBin(v.str); break;
      case Json::Arr:
        PackArrayHeader(v.arr.size());
        for (const auto& x : v.arr) PackValue(x);
        break;
      case Json::Obj:
        PackMapHeader(v.obj.size());
        for (const auto& kv : v.obj) {
          PackStr(kv.first);
          PackValue(kv.second);
        }
        break;
    }
  }

 private:
  void PackBE16(uint16_t v) {
    out += static_cast<char>(v >> 8);
    out += static_cast<char>(v & 0xff);
  }
  void PackBE32(uint32_t v) {
    for (int s = 24; s >= 0; s -= 8) out += static_cast<char>((v >> s) & 0xff);
  }
  void PackBE64(uint64_t v) {
    for (int s = 56; s >= 0; s -= 8) out += static_cast<char>((v >> s) & 0xff);
  }
};

// ---------------------------------------------------------- msgpack unpack
class MsgpackReader {
 public:
  explicit MsgpackReader(const std::string& s) : s_(s) {}

  Json Read() {
    uint8_t t = Byte();
    if (t <= 0x7f) return Json(static_cast<double>(t));           // posfixint
    if (t >= 0xe0) return Json(static_cast<double>(static_cast<int8_t>(t)));
    if (t >= 0x80 && t <= 0x8f) return ReadMap(t & 0x0f);         // fixmap
    if (t >= 0x90 && t <= 0x9f) return ReadArray(t & 0x0f);       // fixarray
    if (t >= 0xa0 && t <= 0xbf) return ReadStr(t & 0x1f);         // fixstr
    switch (t) {
      case 0xc0: return Json();
      case 0xc2: return Json(false);
      case 0xc3: return Json(true);
      case 0xc4: return ReadBin(BE8());
      case 0xc5: return ReadBin(BE16());
      case 0xc6: return ReadBin(BE32());
      case 0xca: {
        uint32_t bits = BE32();
        float f;
        memcpy(&f, &bits, 4);
        return Json(static_cast<double>(f));
      }
      case 0xcb: {
        uint64_t bits = BE64();
        double d;
        memcpy(&d, &bits, 8);
        return Json(d);
      }
      case 0xcc: return Json(static_cast<double>(BE8()));
      case 0xcd: return Json(static_cast<double>(BE16()));
      case 0xce: return Json(static_cast<double>(BE32()));
      case 0xcf: return Json(static_cast<double>(BE64()));
      case 0xd0: return Json(static_cast<double>(static_cast<int8_t>(BE8())));
      case 0xd1: return Json(static_cast<double>(static_cast<int16_t>(BE16())));
      case 0xd2: return Json(static_cast<double>(static_cast<int32_t>(BE32())));
      case 0xd3: return Json(static_cast<double>(static_cast<int64_t>(BE64())));
      case 0xd9: return ReadStr(BE8());
      case 0xda: return ReadStr(BE16());
      case 0xdb: return ReadStr(BE32());
      case 0xdc: return ReadArray(BE16());
      case 0xdd: return ReadArray(BE32());
      case 0xde: return ReadMap(BE16());
      case 0xdf: return ReadMap(BE32());
      default:
        throw std::runtime_error("msgpack: unsupported type byte");
    }
  }

 private:
  const std::string& s_;
  size_t i_ = 0;

  uint8_t Byte() {
    if (i_ >= s_.size()) throw std::runtime_error("msgpack: truncated");
    return static_cast<uint8_t>(s_[i_++]);
  }
  uint64_t BE8() { return Byte(); }
  uint64_t BE16() { uint64_t v = Byte(); return (v << 8) | Byte(); }
  uint64_t BE32() {
    uint64_t v = 0;
    for (int k = 0; k < 4; k++) v = (v << 8) | Byte();
    return v;
  }
  uint64_t BE64() {
    uint64_t v = 0;
    for (int k = 0; k < 8; k++) v = (v << 8) | Byte();
    return v;
  }
  std::string Raw(size_t n) {
    if (i_ + n > s_.size()) throw std::runtime_error("msgpack: truncated");
    std::string out = s_.substr(i_, n);
    i_ += n;
    return out;
  }
  Json ReadStr(size_t n) {
    Json j;
    j.type = Json::Str;
    j.str = Raw(n);
    return j;
  }
  Json ReadBin(size_t n) { return Json::Bytes(Raw(n)); }
  Json ReadArray(size_t n) {
    Json j;
    j.type = Json::Arr;
    j.arr.reserve(n);
    for (size_t k = 0; k < n; k++) j.arr.push_back(Read());
    return j;
  }
  Json ReadMap(size_t n) {
    Json j = Json::Object();
    for (size_t k = 0; k < n; k++) {
      Json key = Read();
      j.obj[key.type == Json::Str ? key.str : key.Dump()] = Read();
    }
    return j;
  }
};

// -------------------------------------------------------- typed conversions
// The typed task API (reference: cpp/include/ray/api.h — ray::Task(fn)
// .Remote(native args) with typed ObjectRef<T> returns): native C++ values
// convert to/from the wire value automatically, so call sites never touch
// Json when they don't want to.
inline Json ToJson(const Json& v) { return v; }
inline Json ToJson(bool v) { return Json(v); }
inline Json ToJson(const char* v) { return Json(v); }
inline Json ToJson(const std::string& v) { return Json(v); }
template <typename T,
          typename std::enable_if<std::is_arithmetic<T>::value, int>::type = 0>
Json ToJson(T v) { return Json(static_cast<double>(v)); }
template <typename T>
Json ToJson(const std::map<std::string, T>& v);  // fwd: vector<map<...>> args
template <typename T>
Json ToJson(const std::vector<T>& v) {
  std::vector<Json> items;
  items.reserve(v.size());
  for (const auto& x : v) items.push_back(ToJson(x));
  return Json::Array(std::move(items));
}
template <typename T>
Json ToJson(const std::map<std::string, T>& v) {
  Json o = Json::Object();
  for (const auto& kv : v) o.obj[kv.first] = ToJson(kv.second);
  return o;
}

template <typename T>
struct FromJsonImpl;
template <> struct FromJsonImpl<Json> {
  static Json Get(const Json& j) { return j; }
};
template <> struct FromJsonImpl<double> {
  static double Get(const Json& j) { return j.AsNum(); }
};
template <> struct FromJsonImpl<long> {
  static long Get(const Json& j) { return j.AsInt(); }
};
template <> struct FromJsonImpl<int> {
  static int Get(const Json& j) { return static_cast<int>(j.AsInt()); }
};
template <> struct FromJsonImpl<bool> {
  static bool Get(const Json& j) {
    if (j.type != Json::Bool) throw std::runtime_error("value: not a bool");
    return j.b;
  }
};
template <> struct FromJsonImpl<std::string> {
  static std::string Get(const Json& j) { return j.AsStr(); }
};
template <typename T> struct FromJsonImpl<std::vector<T>> {
  static std::vector<T> Get(const Json& j) {
    if (j.type != Json::Arr) throw std::runtime_error("value: not an array");
    std::vector<T> out;
    out.reserve(j.arr.size());
    for (const auto& x : j.arr) out.push_back(FromJsonImpl<T>::Get(x));
    return out;
  }
};
template <typename T>
T FromJson(const Json& j) { return FromJsonImpl<T>::Get(j); }

// ----------------------------------------------------------------- client
struct ObjectRef {
  std::string id;
};

template <typename T>
struct TypedRef {  // typed ObjectRef (reference: ray::ObjectRef<T>)
  std::string id;
};

class Client;

class TaskCaller {
 public:
  TaskCaller(Client* c, std::string func) : c_(c), func_(std::move(func)) {}
  template <typename... A>
  Json Remote(A&&... args);  // call-and-wait (reference Task().Remote + Get)
  template <typename... A>
  ObjectRef RemoteAsync(A&&... args);  // returns a ref; Get() later

 private:
  Client* c_;
  std::string func_;
};

// Typed task caller: native args in, R out (reference: the templated
// ray::Task(fn).Remote() whose ObjectRef carries the return type).
template <typename R>
class TypedTaskCaller {
 public:
  TypedTaskCaller(Client* c, std::string func)
      : inner_(c, std::move(func)) {}
  template <typename... A>
  R Remote(A&&... args) {
    return FromJson<R>(inner_.Remote(ToJson(std::forward<A>(args))...));
  }
  template <typename... A>
  TypedRef<R> RemoteAsync(A&&... args) {
    return TypedRef<R>{
        inner_.RemoteAsync(ToJson(std::forward<A>(args))...).id};
  }

 private:
  TaskCaller inner_;
};

class Actor {
 public:
  Actor() {}
  Actor(Client* c, std::string id) : c_(c), id_(std::move(id)) {}
  template <typename... A>
  Json Call(const std::string& method, A&&... args);
  void Kill();
  const std::string& Id() const { return id_; }

 private:
  Client* c_ = nullptr;  // Call/Kill on a default-constructed Actor throws
  std::string id_;
};

class Client {
 public:
  Client(const std::string& host, int port, const std::string& token) {
    fd_ = socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) throw std::runtime_error("socket() failed");
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1)
      throw std::runtime_error("bad host: " + host);
    if (connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof addr) != 0)
      throw std::runtime_error("connect failed");
    Handshake();
    // authenticate on the control plane (op 1), like any worker
    Json hello = Json::Object();
    hello.obj["token"] = Json(token);
    hello.obj["kind"] = Json("xlang");
    Request(kOpHello, hello);
  }
  ~Client() {
    if (fd_ >= 0) close(fd_);
  }
  Client(const Client&) = delete;
  Client& operator=(const Client&) = delete;

  int WireVersion() const { return agreed_version_; }

  TaskCaller Task(const std::string& func) { return TaskCaller(this, func); }

  // Typed variant: rtpu::Json never appears at the call site —
  //   double r = c.TypedTask<double>("add").Remote(3, 4);
  template <typename R>
  TypedTaskCaller<R> TypedTask(const std::string& func) {
    return TypedTaskCaller<R>(this, func);
  }

  Actor ActorCreate(const std::string& cls, std::vector<Json> args = {}) {
    Json m = Json::Object();
    m.obj["cls"] = Json(cls);
    m.obj["args"] = Json::Array(std::move(args));
    return Actor(this, Request(kOpXlActorCreate, m)["actor"].AsStr());
  }

  ObjectRef Put(const Json& value) {
    Json m = Json::Object();
    m.obj["value"] = value;
    return ObjectRef{Request(kOpXlPut, m)["ref"].AsStr()};
  }

  Json Get(const ObjectRef& ref) {
    Json m = Json::Object();
    m.obj["ref"] = Json(ref.id);
    return Request(kOpXlGet, m);
  }

  template <typename T>
  T Get(const TypedRef<T>& ref) {
    return FromJson<T>(Get(ObjectRef{ref.id}));
  }

  // Release the server-held borrow for a Put()/RemoteAsync() ref; without
  // this a long-lived client pins every object for the server's lifetime.
  void Free(const ObjectRef& ref) {
    Json m = Json::Object();
    m.obj["ref"] = Json(ref.id);
    Request(kOpXlFree, m);
  }

  template <typename T>
  void Free(const TypedRef<T>& ref) { Free(ObjectRef{ref.id}); }

  std::vector<std::string> ListFuncs() {
    Json r = Request(kOpXlListFuncs, Json::Object());
    std::vector<std::string> out;
    for (auto& f : r["funcs"].arr) out.push_back(f.AsStr());
    return out;
  }

  // one in-flight request per client (callers wanting parallelism open
  // multiple clients — connections are cheap)
  Json Request(int op_num, const Json& payload) {
    uint64_t mid = ++next_id_;
    MsgpackWriter w;
    w.PackArrayHeader(4);
    w.PackInt(kRequest);
    w.PackInt(static_cast<int64_t>(mid));
    w.PackInt(op_num);
    w.PackValue(payload);
    SendFrame(w.out);
    while (true) {
      Json frame = RecvFrame();
      long kind = frame.arr.at(0).AsInt();
      if (kind == kNotify) continue;  // pushed notifications: not ours
      if (kind == kGoodbye)
        throw std::runtime_error("server closed: " + frame.arr.at(1).AsStr());
      if (frame.arr.size() < 3 ||
          static_cast<uint64_t>(frame.arr.at(1).AsInt()) != mid)
        throw std::runtime_error("rpc: out-of-order reply");
      if (kind == kError)
        throw std::runtime_error("remote error: " + frame.arr.at(2).AsStr());
      if (kind != kReply) throw std::runtime_error("rpc: unexpected frame");
      return frame.arr.at(2);
    }
  }

 private:
  void Handshake() {
    // both ends fire HELLO immediately; agree on min(max_a, max_b)
    MsgpackWriter w;
    w.PackArrayHeader(5);
    w.PackInt(kHello);
    w.PackStr(kWireMagic);
    w.PackInt(kWireVersionMin);
    w.PackInt(kWireVersionMax);
    w.PackMapHeader(0);
    SendFrame(w.out);
    Json frame = RecvFrame();
    long kind = frame.arr.at(0).AsInt();
    if (kind == kGoodbye)
      throw std::runtime_error("server refused: " + frame.arr.at(1).AsStr());
    if (kind != kHello || frame.arr.size() < 4)
      throw std::runtime_error("rpc: expected hello frame");
    if (frame.arr.at(1).AsStr() != kWireMagic)
      throw std::runtime_error("rpc: bad protocol magic");
    long peer_min = frame.arr.at(2).AsInt();
    long peer_max = frame.arr.at(3).AsInt();
    long agreed = peer_max < kWireVersionMax ? peer_max : kWireVersionMax;
    long floor_ = peer_min > kWireVersionMin ? peer_min : kWireVersionMin;
    if (agreed < floor_)
      throw std::runtime_error(
          "wire schema version mismatch: client supports [" +
          std::to_string(kWireVersionMin) + ", " +
          std::to_string(kWireVersionMax) + "], server supports [" +
          std::to_string(peer_min) + ", " + std::to_string(peer_max) + "]");
    agreed_version_ = static_cast<int>(agreed);
  }

  void SendFrame(const std::string& body) {
    uint32_t n = htonl(static_cast<uint32_t>(body.size()));
    SendAll(reinterpret_cast<const char*>(&n), 4);
    SendAll(body.data(), body.size());
  }
  Json RecvFrame() {
    char hdr[4];
    RecvAll(hdr, 4);
    uint32_t len;
    memcpy(&len, hdr, 4);
    len = ntohl(len);
    if (len > kMaxFrame)
      // e.g. an HTTP response's first bytes parsed as a length — reject
      // before allocating gigabytes (matches codec.py unpack_header)
      throw std::runtime_error(
          "rpc: frame length " + std::to_string(len) +
          " exceeds MAX_FRAME (not an rtpu endpoint?)");
    std::string body(len, '\0');
    RecvAll(&body[0], len);
    Json frame = MsgpackReader(body).Read();
    if (frame.type != Json::Arr || frame.arr.empty())
      throw std::runtime_error("rpc: malformed frame");
    return frame;
  }
  void SendAll(const char* p, size_t n) {
    while (n) {
      ssize_t k = send(fd_, p, n, 0);
      if (k <= 0) throw std::runtime_error("send failed");
      p += k;
      n -= static_cast<size_t>(k);
    }
  }
  void RecvAll(char* p, size_t n) {
    while (n) {
      ssize_t k = recv(fd_, p, n, 0);
      if (k <= 0) throw std::runtime_error("recv failed (server closed?)");
      p += k;
      n -= static_cast<size_t>(k);
    }
  }
  int fd_ = -1;
  int agreed_version_ = 0;
  uint64_t next_id_ = 0;
};

template <typename... A>
Json TaskCaller::Remote(A&&... args) {
  Json m = Json::Object();
  m.obj["func"] = Json(func_);
  m.obj["args"] = Json::Array({Json(std::forward<A>(args))...});
  return c_->Request(kOpXlCall, m);
}

template <typename... A>
ObjectRef TaskCaller::RemoteAsync(A&&... args) {
  Json m = Json::Object();
  m.obj["func"] = Json(func_);
  m.obj["args"] = Json::Array({Json(std::forward<A>(args))...});
  return ObjectRef{c_->Request(kOpXlSubmit, m)["ref"].AsStr()};
}

template <typename... A>
Json Actor::Call(const std::string& method, A&&... args) {
  if (c_ == nullptr) throw std::runtime_error("Actor not initialized");
  Json m = Json::Object();
  m.obj["actor"] = Json(id_);
  m.obj["method"] = Json(method);
  m.obj["args"] = Json::Array({Json(std::forward<A>(args))...});
  return c_->Request(kOpXlActorCall, m);
}

inline void Actor::Kill() {
  if (c_ == nullptr) throw std::runtime_error("Actor not initialized");
  Json m = Json::Object();
  m.obj["actor"] = Json(id_);
  c_->Request(kOpXlKillActor, m);
}

inline Client Init(const std::string& host, int port, const std::string& token) {
  return Client(host, port, token);
}

}  // namespace rtpu
