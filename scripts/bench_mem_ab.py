#!/usr/bin/env python
"""Interleaved memory-accounting overhead A/B (MICROBENCH.md round 16).

Measures the ISSUE-18 store-ledger cost on the two paths it rides:

1. ``plane_pull_64mb`` — MB/s of a 64 MB ``PlaneClient.pull_into`` landing
   in a local store over a live loopback plane server (the seal +
   mark-secondary ledger sites fire once per pulled object);
2. ``shuffle`` — rows/s of a full ``Dataset.random_shuffle`` exchange
   through a live session (every block put/pin/get crosses the ledger).

Accounting is a module-import gate (``RAY_TPU_MEM_ACCOUNTING``), so each
arm runs in a FRESH process; interleave arms by alternating invocations:

    python scripts/bench_mem_ab.py --arm on
    python scripts/bench_mem_ab.py --arm off

Single-run numbers on a shared core are noise — compare medians across
3 alternating rounds per arm.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time


def bench_pull(size_mb: int, repeats: int) -> list[float]:
    import numpy as np

    from ray_tpu._private.ids import ObjectID
    from ray_tpu.core.object_plane import ObjectPlaneServer, PlaneClient
    from ray_tpu.core.shm_store import SharedMemoryStore

    nbytes = size_mb << 20
    slack = 16 << 20
    tag = f"{os.getpid()}_{size_mb}"
    # sized for every repeat: the plane server's read pin defers each
    # delete, so per-repeat space is not reliably back before the next put
    src = SharedMemoryStore(f"/rtpu_memab_src_{tag}",
                            size=repeats * nbytes + slack, owner=True)
    dst = SharedMemoryStore(f"/rtpu_memab_dst_{tag}",
                            size=repeats * nbytes + slack, owner=True)
    server = ObjectPlaneServer(src)
    client = PlaneClient()
    try:
        payload = np.random.default_rng(0).bytes(nbytes)
        rates = []
        for _ in range(repeats):
            oid = ObjectID(os.urandom(ObjectID.SIZE))
            src.put_bytes(oid, payload)
            t0 = time.perf_counter()
            status = client.pull_into([server.address], oid, dst)
            dt = time.perf_counter() - t0
            assert status == "sealed", status
            rates.append(round(nbytes / dt / 1e6, 1))
            src.delete(oid)
        return rates
    finally:
        client.close()
        server.close()
        src.close()
        dst.close()


def bench_shuffle(rows: int, repeats: int) -> list[float]:
    import ray_tpu
    from ray_tpu import data as rdata

    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    try:
        # warm: pool spawn + import cost stays out of the measured rounds
        rdata.range(200, parallelism=4).random_shuffle(seed=0).take_all()
        rates = []
        for i in range(repeats):
            t0 = time.perf_counter()
            out = rdata.range(rows, parallelism=8) \
                       .random_shuffle(seed=i).take_all()
            dt = time.perf_counter() - t0
            assert len(out) == rows
            rates.append(round(rows / dt, 1))
        return rates
    finally:
        ray_tpu.shutdown()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--arm", choices=("on", "off"), required=True)
    ap.add_argument("--size-mb", type=int, default=64)
    ap.add_argument("--rows", type=int, default=20000)
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()

    os.environ["RAY_TPU_MEM_ACCOUNTING"] = "1" if args.arm == "on" else "0"
    pull = bench_pull(args.size_mb, args.repeats)
    shuffle = bench_shuffle(args.rows, args.repeats)
    print(json.dumps({
        "arm": args.arm,
        "plane_pull_mb_per_s": pull,
        "plane_pull_median": round(statistics.median(pull), 1),
        "shuffle_rows_per_s": shuffle,
        "shuffle_median": round(statistics.median(shuffle), 1),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.path.append(os.getcwd())
    sys.exit(main())
