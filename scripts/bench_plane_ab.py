#!/usr/bin/env python
"""Interleaved seed-vs-PR object-plane pull A/B (MICROBENCH.md methodology).

Measures MB/s for a pull landing in the local store, over live loopback
plane servers. Runs against whichever tree is on PYTHONPATH and adapts:

- new tree: ``PlaneClient.pull_into`` (zero-copy v3 BLOB path);
- seed tree: ``PlaneClient.pull`` -> ``put_bytes`` (the old five-copy path,
  exactly as runtime._pull_from_plane consumed it).

Interleave by alternating invocations of this script between two checkouts
on the same box; single-run numbers on a shared core are noise.

    PYTHONPATH=/path/to/tree python scripts/bench_plane_ab.py --size-mb 64
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def bench(size_mb: int, holders: int, repeats: int) -> None:
    import numpy as np

    from ray_tpu._private.ids import ObjectID
    from ray_tpu.core.object_plane import ObjectPlaneServer, PlaneClient
    from ray_tpu.core.shm_store import SharedMemoryStore

    nbytes = size_mb << 20
    slack = 16 << 20
    tag = f"{os.getpid()}_{size_mb}_{holders}"
    srcs = [SharedMemoryStore(f"/rtpu_ab_src{i}_{tag}", size=nbytes + slack,
                              owner=True) for i in range(holders)]
    dst = SharedMemoryStore(f"/rtpu_ab_dst_{tag}",
                            size=repeats * nbytes + slack, owner=True)
    servers = [ObjectPlaneServer(s) for s in srcs]
    zero_copy = hasattr(PlaneClient, "pull_into")
    client = PlaneClient()
    if zero_copy and holders > 1:
        client = PlaneClient(stripe_min_bytes=1)
    try:
        payload = np.random.default_rng(0).bytes(nbytes)
        addrs = [srv.address for srv in servers]
        rates = []
        for _ in range(repeats):
            oid = ObjectID(os.urandom(ObjectID.SIZE))
            for s in srcs:
                s.put_bytes(oid, payload)
            t0 = time.perf_counter()
            if zero_copy:
                status = client.pull_into(addrs, oid, dst)
                assert status == "sealed", status
            else:
                blob = client.pull(addrs, oid)
                assert blob is not None
                dst.put_bytes(oid, blob)
            dt = time.perf_counter() - t0
            assert bytes(dst.get_bytes(oid)) == payload
            rates.append(round(nbytes / dt / 1e6, 1))
            for s in srcs:
                s.delete(oid)
        print(json.dumps({
            "tree": "pull_into_v3" if zero_copy else "seed_pull_putbytes",
            "metric": f"plane_pull_{size_mb}mb_{holders}h",
            "mb_per_s": rates, "median": sorted(rates)[len(rates) // 2],
            "unit": "MB/s",
        }), flush=True)
    finally:
        client.close()
        for srv in servers:
            srv.close()
        for s in srcs:
            s.close()
        dst.close()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--size-mb", type=int, default=64)
    ap.add_argument("--holders", type=int, default=1)
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()
    # AFTER PYTHONPATH, never ahead of it: the whole point is that the
    # operator's PYTHONPATH selects which tree (seed vs PR) is measured
    sys.path.append(os.getcwd())
    bench(args.size_mb, args.holders, args.repeats)
