#!/usr/bin/env python
"""Interleaved serve-anatomy overhead A/B (MICROBENCH round 14).

Measures front-door serving throughput with the ISSUE-16 request anatomy
ON (default) vs OFF (``RAY_TPU_SERVE_ANATOMY=0`` — switches off every
stamping site: admit, router_stamp, replica_dequeue, engine first-token,
KV windows, complete). Each arm runs in a FRESH process (the gate is read
at module import); interleave arms by alternating invocations:

    python scripts/bench_serve_anatomy_ab.py --arm on  --requests 120
    python scripts/bench_serve_anatomy_ab.py --arm off --requests 120

The metric is tokens/s over the full production path — HTTP proxy ->
router -> replica -> engine, SSE streaming (CPU byte-tokenizer fallback
model, short decodes) — so the per-request stamping cost shows up
undiluted by long decode loops.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor

PORT = int(os.environ.get("RAY_TPU_SERVE_BENCH_PORT", "18473"))


def _stream_tokens(url: str, body: dict) -> int:
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    n = 0
    with urllib.request.urlopen(req, timeout=120) as resp:
        for raw in resp:
            line = raw.decode().strip()
            if line.startswith("data: ") and line != "data: [DONE]":
                n += 1
    return n


def bench(requests: int, max_tokens: int, repeats: int,
          concurrency: int) -> list[float]:
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    app = serve.build_openai_app()
    serve.run(app, route_prefix="/v1")
    serve.start_http_proxy(port=PORT)
    url = f"http://127.0.0.1:{PORT}/v1/chat/completions"
    body = {"messages": [{"role": "user", "content": "anatomy ab"}],
            "max_tokens": max_tokens, "stream": True}

    pool = ThreadPoolExecutor(max_workers=concurrency)
    # warm: model build + route table + SSE path
    list(pool.map(lambda _: _stream_tokens(url, body), range(16)))
    rates = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        toks = sum(pool.map(lambda _: _stream_tokens(url, body),
                            range(requests)))
        rates.append(toks / (time.perf_counter() - t0))
    pool.shutdown(wait=False)
    serve.shutdown()
    ray_tpu.shutdown()
    return rates


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--arm", choices=("on", "off"), required=True)
    ap.add_argument("--requests", type=int, default=120)
    ap.add_argument("--max-tokens", type=int, default=8)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--concurrency", type=int, default=8)
    args = ap.parse_args()

    os.environ["RAY_TPU_SERVE_ANATOMY"] = "1" if args.arm == "on" else "0"
    rates = bench(args.requests, args.max_tokens, args.repeats,
                  args.concurrency)
    out = {"arm": args.arm, "requests": args.requests,
           "max_tokens": args.max_tokens,
           "rates": [round(r, 1) for r in rates],
           "median_tokens_per_s": round(statistics.median(rates), 1)}
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
