#!/usr/bin/env python
"""Export a session's cluster timeline as one Perfetto/Chrome trace file.

Two sources:

- ``--url http://HEAD:8265`` — fetch ``GET /api/v0/timeline`` from a live
  dashboard (the normal operator path: works from any machine that can
  reach the head).
- no ``--url`` — run INSIDE a driver process' session: imports ray_tpu and
  exports the current runtime's timeline directly (same as
  ``ray_tpu.util.state.timeline(path)``).

Load the output in https://ui.perfetto.dev or chrome://tracing. Lanes:
process = node (head is pid 1), thread = worker pid / stable actor lane;
flow arrows join each task's head-side dispatch to its worker exec window;
cross-node timestamps are re-based onto the head clock (heartbeat-derived
offsets — see README "Observability > Cluster timeline" for the caveats).

    python scripts/timeline.py --url http://127.0.0.1:8265 -o trace.json
"""

from __future__ import annotations

import argparse
import json
import sys


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", default=None,
                    help="dashboard base url (e.g. http://127.0.0.1:8265); "
                         "omit to export from an in-process session")
    ap.add_argument("-o", "--out", default="timeline.json",
                    help="output trace file (default: timeline.json)")
    ap.add_argument("--timeout", type=float, default=60.0)
    args = ap.parse_args()

    if args.url:
        import urllib.request

        url = args.url.rstrip("/") + "/api/v0/timeline"
        with urllib.request.urlopen(url, timeout=args.timeout) as r:
            trace = json.load(r)
        if isinstance(trace, dict) and trace.get("error"):
            print(f"timeline export failed: {trace['error']}",
                  file=sys.stderr)
            return 1
        with open(args.out, "w") as f:
            json.dump(trace, f)
    else:
        import ray_tpu  # noqa: F401 — must already be init'd in-session
        from ray_tpu.util import state

        trace = state.timeline(args.out)

    cats = sorted({e.get("cat") for e in trace if e.get("cat")})
    print(f"wrote {args.out}: {len(trace)} events, categories: "
          f"{', '.join(cats)}")
    print("open in https://ui.perfetto.dev or chrome://tracing")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
