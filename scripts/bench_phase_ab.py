#!/usr/bin/env python
"""Interleaved phase-stamping overhead A/B (MICROBENCH.md round 12).

Measures process-worker task throughput with the ISSUE-13 timeline phase
stamping ON (default) vs OFF (``RAY_TPU_TASK_PHASES=0`` — switches off the
monotonic reads, the clocks element on the done reply, and the parent-side
ring append). Each arm runs in a FRESH process (the gate is read at module
import); interleave arms by alternating invocations:

    python scripts/bench_phase_ab.py --arm on  --tasks 600
    python scripts/bench_phase_ab.py --arm off --tasks 600

The metric is end-to-end tasks/s of trivial process tasks — the dispatch
path the 4 extra monotonic reads + 4 floats on the reply pipe ride on, so
any regression shows up undiluted by task work.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time


def bench(tasks: int, repeats: int) -> list[float]:
    import ray_tpu

    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)

    @ray_tpu.remote
    def nop(x):
        return x

    # warm the pool (spawn + import cost must not land in the measured arm)
    ray_tpu.get([nop.remote(i) for i in range(32)], timeout=120)
    rates = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        ray_tpu.get([nop.remote(i) for i in range(tasks)], timeout=300)
        rates.append(tasks / (time.perf_counter() - t0))
    ray_tpu.shutdown()
    return rates


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--arm", choices=("on", "off"), required=True)
    ap.add_argument("--tasks", type=int, default=600)
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()

    os.environ["RAY_TPU_TASK_PHASES"] = "1" if args.arm == "on" else "0"
    rates = bench(args.tasks, args.repeats)
    out = {"arm": args.arm, "tasks": args.tasks,
           "rates": [round(r, 1) for r in rates],
           "median_tasks_per_s": round(statistics.median(rates), 1)}
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
