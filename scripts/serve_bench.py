#!/usr/bin/env python
"""Serve north-star benchmark: req/s + TTFT over the OpenAI ingress.

Parity: the reference's serve release workloads
(release/serve_tests/workloads/) which gate serve regressions on sustained
req/s and latency percentiles. Runs the full production path — HTTP proxy ->
router -> deployment replica -> LLM engine (CPU byte-tokenizer fallback
model, so the artifact is hermetic and hardware-independent) — and emits
``SERVE_BENCH.json`` at the repo root:

    {"req_per_s": ..., "ttft_p50_ms": ..., "ttft_p99_ms": ..., ...}

Usage: python scripts/serve_bench.py [--requests N] [--concurrency C]
       [--stream-samples K] [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
PORT = int(os.environ.get("RAY_TPU_SERVE_BENCH_PORT", "18470"))


def _post(url: str, body: dict, timeout: float = 120.0) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _ttft_ms(url: str, body: dict, timeout: float = 120.0) -> float:
    """Time-to-first-token over the SSE streaming path, in milliseconds."""
    body = dict(body, stream=True)
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    t0 = time.perf_counter()
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        for raw in resp:
            line = raw.decode().strip()
            if line.startswith("data: ") and line != "data: [DONE]":
                return (time.perf_counter() - t0) * 1000.0
    raise RuntimeError("stream produced no data frames")


def _throughput(url: str, body: dict, n: int, concurrency: int) -> dict:
    """Sustained closed-loop req/s with per-request latency percentiles."""
    latencies: list[float] = []
    errors = [0]
    lock = threading.Lock()
    it = iter(range(n))

    def worker():
        while True:
            with lock:
                try:
                    next(it)
                except StopIteration:
                    return
            t0 = time.perf_counter()
            try:
                _post(url, body)
            except Exception:
                with lock:
                    errors[0] += 1
                continue
            dt = (time.perf_counter() - t0) * 1000.0
            with lock:
                latencies.append(dt)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    done = len(latencies)
    lat = sorted(latencies) or [0.0]

    def pct(p):
        return round(lat[min(len(lat) - 1, int(p * len(lat)))], 2)

    return {
        "requests": n, "completed": done, "errors": errors[0],
        "concurrency": concurrency, "wall_s": round(wall, 3),
        "req_per_s": round(done / wall, 2) if wall > 0 else 0.0,
        "latency_p50_ms": pct(0.50), "latency_p99_ms": pct(0.99),
    }


def run(requests: int, concurrency: int, stream_samples: int,
        max_tokens: int = 8) -> dict:
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    app = serve.build_openai_app()  # default config: CPU-model fallback
    serve.run(app, route_prefix="/v1")
    proxy = serve.start_http_proxy(port=PORT)
    base = f"http://127.0.0.1:{PORT}/v1"
    chat_body = {
        "messages": [{"role": "user", "content": "benchmark prompt"}],
        "max_tokens": max_tokens,
    }

    # warm: model build + route table + first compile
    _post(f"{base}/chat/completions", chat_body)

    # TTFT over the streaming path (sequential: measures the ingress->first-
    # delta critical path, not queueing)
    ttfts = [_ttft_ms(f"{base}/chat/completions", chat_body)
             for _ in range(stream_samples)]
    ttfts.sort()

    def pct(vals, p):
        return round(vals[min(len(vals) - 1, int(p * len(vals)))], 2)

    # sustained closed-loop throughput on the non-streaming path
    tput = _throughput(f"{base}/chat/completions", chat_body,
                       requests, concurrency)

    result = {
        "bench": "serve_openai_ingress",
        "model": "cpu-byte-fallback",
        "max_tokens": max_tokens,
        "ttft_samples": stream_samples,
        "ttft_p50_ms": pct(ttfts, 0.50),
        "ttft_p99_ms": pct(ttfts, 0.99),
        "ttft_mean_ms": round(statistics.fmean(ttfts), 2),
        **tput,
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    try:
        proxy.stop()
    except Exception:
        pass
    ray_tpu.shutdown()
    return result


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--requests", type=int, default=300)
    parser.add_argument("--concurrency", type=int, default=8)
    parser.add_argument("--stream-samples", type=int, default=50)
    parser.add_argument("--max-tokens", type=int, default=8)
    parser.add_argument("--quick", action="store_true",
                        help="smoke sizes (CI)")
    parser.add_argument("--out", default=os.path.join(REPO, "SERVE_BENCH.json"))
    args = parser.parse_args()
    if args.quick:
        args.requests, args.stream_samples = 30, 8
    result = run(args.requests, args.concurrency, args.stream_samples,
                 args.max_tokens)
    print(json.dumps(result, indent=2))
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
