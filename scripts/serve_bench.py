#!/usr/bin/env python
"""Serve north-star benchmark: OPEN-LOOP arrival-rate sweep + PD A/B.

Parity: the reference's serve release workloads
(release/serve_tests/workloads/) which gate serving on sustained tokens/s
and latency under a load generator that does NOT slow down when the server
does (open loop — Poisson arrivals at a fixed offered rate; a closed loop
self-throttles and hides the overload knee).

Two benches, one artifact (``SERVE_BENCH.json``):

1. **Ingress sweep** — the full production path (HTTP proxy -> router ->
   replica -> engine; CPU byte-tokenizer fallback model, hermetic) swept
   across offered arrival rates. Per rate: tokens/s, goodput under the
   TTFT SLO (completed req/s whose TTFT met the budget), client-side
   p50/p99 TTFT over the SSE streaming path, and end-to-end latency
   percentiles. Replaces the old single closed-loop ~53 req/s TTFT point.
2. **PD A/B** — disaggregated prefill/decode (serve/pd.py deployments +
   kv_transport.py plane handoff) vs the co-located baseline, interleaved
   rounds on the same box at the same offered rate (tiny llama model).
   Disaggregation pays one cross-engine KV hop per request; the A/B pins
   what that hop costs where it matters (TTFT) — the win it buys
   (independent fleet scaling) is a topology property, not a same-box one.

Usage: python scripts/serve_bench.py [--rates 2,8,16,32] [--duration 8]
       [--slo-ttft-ms 250] [--max-tokens 8] [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import statistics
import sys
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
PORT = int(os.environ.get("RAY_TPU_SERVE_BENCH_PORT", "18470"))


def _post(url: str, body: dict, timeout: float = 120.0) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


# ------------------------------------------------------------ open-loop core
def _open_loop(fire, rate_rps: float, duration_s: float, *, seed: int = 0,
               max_workers: int = 1024) -> tuple[list, float]:
    """Fire ``fire(sched_t)`` at Poisson arrivals of ``rate_rps`` for
    ``duration_s`` seconds, never waiting for completions (open loop).
    ``fire`` receives its request's SCHEDULED arrival time (perf_counter
    base) and must clock latency from it — so any client-side queueing
    (worker-pool backlog under server overload) counts against TTFT
    instead of silently self-throttling the offered load back into a
    closed loop and hiding the knee. The pool is sized to the arrival
    count (capped) so every scheduled request can be outstanding at once.
    Returns (per-request records, wall seconds incl. the drain tail)."""
    rng = random.Random(seed)
    arrivals, t = [], 0.0
    while True:
        t += rng.expovariate(rate_rps)
        if t >= duration_s:
            break
        arrivals.append(t)
    pool = ThreadPoolExecutor(
        max_workers=min(max_workers, max(1, len(arrivals))))
    futs = []
    t0 = time.perf_counter()
    for at in arrivals:
        delay = t0 + at - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        futs.append(pool.submit(fire, t0 + at))
    records = [f.result() for f in futs]
    wall = time.perf_counter() - t0
    pool.shutdown(wait=False)
    return records, wall


def _pct(sorted_vals: list, p: float) -> float:
    if not sorted_vals:
        return 0.0
    return round(sorted_vals[min(len(sorted_vals) - 1,
                                 int(p * len(sorted_vals)))], 2)


def _point(records: list, wall: float, rate: float, slo_ttft_ms: float,
           tokens_per_req: int) -> dict:
    ok = [r for r in records if r.get("ok")]
    ttfts = sorted(r["ttft_ms"] for r in ok)
    lats = sorted(r["latency_ms"] for r in ok)
    good = sum(1 for r in ok if r["ttft_ms"] <= slo_ttft_ms)
    return {
        "rate_rps": rate,
        "offered": len(records),
        "completed": len(ok),
        "errors": len(records) - len(ok),
        "wall_s": round(wall, 3),
        "tokens_per_s": round(len(ok) * tokens_per_req / wall, 2)
        if wall > 0 else 0.0,
        "goodput_rps": round(good / wall, 2) if wall > 0 else 0.0,
        "ttft_p50_ms": _pct(ttfts, 0.50),
        "ttft_p99_ms": _pct(ttfts, 0.99),
        "latency_p50_ms": _pct(lats, 0.50),
        "latency_p99_ms": _pct(lats, 0.99),
    }


# ------------------------------------------------------------- ingress sweep
def _fire_stream(url: str, body: dict, timeout: float = 120.0,
                 sched_t: float | None = None) -> dict:
    """One SSE streaming request: client-side TTFT (first data frame) +
    total latency; the stream is drained so the request really completes.
    Clocks start at ``sched_t`` (the open-loop scheduled arrival) when
    given, so pre-send queueing is part of the measurement."""
    body = dict(body, stream=True)
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    t0 = time.perf_counter() if sched_t is None else sched_t
    ttft = None
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            for raw in resp:
                line = raw.decode().strip()
                if line.startswith("data: "):
                    if ttft is None and line != "data: [DONE]":
                        ttft = (time.perf_counter() - t0) * 1000.0
        if ttft is None:
            return {"ok": False}
        return {"ok": True, "ttft_ms": ttft,
                "latency_ms": (time.perf_counter() - t0) * 1000.0}
    except Exception:
        return {"ok": False}


def _phase_breakdown_since(since_wall: float) -> dict:
    """Per-phase duration quantiles for requests admitted after
    ``since_wall`` (ISSUE 16 anatomy ledgers). Replica-side stamps ride the
    push/reply-pipe beat, so wait one beat before attributing."""
    from ray_tpu.serve import anatomy

    time.sleep(1.2 * float(
        os.environ.get("RAY_TPU_METRICS_PUSH_PERIOD_S", "2") or 2))
    bd = anatomy.phase_breakdown(since_wall=since_wall)
    return {"requests": bd["requests"],
            "phases": {p: {"p50_ms": round(v["p50_ms"], 3),
                           "p99_ms": round(v["p99_ms"], 3), "n": v["n"]}
                       for p, v in bd["phases"].items()}}


def run_ingress_sweep(base: str, rates: list, duration_s: float,
                      slo_ttft_ms: float, max_tokens: int) -> list:
    from ray_tpu import serve
    from ray_tpu.serve import anatomy

    app = serve.build_openai_app()  # default config: CPU-model fallback
    serve.run(app, route_prefix="/v1")
    # AFTER the first serve.run: controller creation resets serve._state,
    # which stops any proxy started before it
    serve.start_http_proxy(port=PORT)
    url = f"{base}/v1/chat/completions"
    body = {"messages": [{"role": "user", "content": "benchmark prompt"}],
            "max_tokens": max_tokens}
    _post(url, body)  # warm: model build + route table + first compile
    _fire_stream(url, body)

    points = []
    for i, rate in enumerate(rates):
        since = anatomy.now_wall()
        records, wall = _open_loop(
            lambda sched: _fire_stream(url, body, sched_t=sched),
            rate, duration_s, seed=17 + i)
        pt = _point(records, wall, rate, slo_ttft_ms, max_tokens)
        pt["phase_breakdown"] = _phase_breakdown_since(since)
        print(f"  ingress rate={rate:g}/s -> {pt['tokens_per_s']} tok/s, "
              f"goodput {pt['goodput_rps']}/s, "
              f"ttft p50/p99 {pt['ttft_p50_ms']}/{pt['ttft_p99_ms']} ms")
        points.append(pt)
    return points


# -------------------------------------------------- front door (ISSUE 17)
def _fd_fleet(count: int, body: dict) -> list:
    """(Re)start the ingress fleet at ``count`` and warm every ingress:
    first request through an ingress builds its epoch handle + compiled
    per-replica dispatch, which must not land inside a measured window."""
    from ray_tpu import serve

    serve.stop_front_door()
    addrs = serve.start_front_door(count=count)
    urls = [f"http://{h}:{p}/v1/chat/completions" for h, p in addrs]
    for u in urls:
        for _ in range(2):  # touch both replicas through each ingress
            _post(u, body)
        _fire_stream(u, body)
    return urls


def _fd_fire_split(urls: list, body: dict, fire_one=_fire_stream):
    """Open-loop ``fire`` splitting arrivals round-robin across the
    ingress fleet (per-ingress arrival split; one merged record stream)."""
    n = {"i": 0}
    lock = threading.Lock()

    def fire(sched):
        with lock:
            i = n["i"]
            n["i"] += 1
        return fire_one(urls[i % len(urls)], body, sched_t=sched)

    return fire


def _fire_raw(url: str, body: dict, timeout: float = 120.0,
              sched_t: float | None = None) -> dict:
    """One non-streaming request; the response is a single shot so TTFT
    and end-to-end latency coincide. Clocks from the scheduled arrival
    when given (client-side queueing stays visible)."""
    t0 = time.perf_counter() if sched_t is None else sched_t
    try:
        out = _post(url, body, timeout=timeout)
        res = out.get("result", out)
        if res.get("ntokens") is None:
            return {"ok": False}
        lat = (time.perf_counter() - t0) * 1000.0
        return {"ok": True, "ttft_ms": lat, "latency_ms": lat}
    except Exception:
        return {"ok": False}


def run_front_door(rates: list, duration_s: float, slo_ttft_ms: float,
                   max_tokens: int, n_ingress: int, ab_rate: float,
                   ab_rounds: int) -> dict:
    """Multi-ingress arm: the same open-loop sweep through a fleet of
    ``n_ingress`` replicated front-door ingresses (each its own PROCESS:
    isolate_process actors with epoch-fed routers — zero control-plane
    RPCs per request), plus an interleaved 1-vs-2-ingress A/B at a fixed
    rate past the single-ingress dispatch ceiling (see the A/B block
    below for why the ceiling is compiled-edge capacity)."""
    from ray_tpu import serve

    serve.run(serve.build_openai_app(num_replicas=2), route_prefix="/v1")
    body = {"messages": [{"role": "user", "content": "benchmark prompt"}],
            "max_tokens": max_tokens}

    urls = _fd_fleet(n_ingress, body)
    points = []
    for i, rate in enumerate(rates):
        records, wall = _open_loop(_fd_fire_split(urls, body), rate,
                                   duration_s, seed=43 + i)
        pt = _point(records, wall, rate, slo_ttft_ms, max_tokens)
        print(f"  front-door x{n_ingress} rate={rate:g}/s -> "
              f"{pt['tokens_per_s']} tok/s, goodput {pt['goodput_rps']}/s, "
              f"ttft p50/p99 {pt['ttft_p50_ms']}/{pt['ttft_p99_ms']} ms")
        points.append(pt)

    # --- interleaved 1-vs-2-ingress A/B on an accelerator-sleep engine ---
    # This box has ONE CPU core (see MICROBENCH.md), so wall-clock CPU
    # parallelism across ingress processes is physically impossible here.
    # What ingress replication buys on any box is per-ingress DISPATCH
    # capacity: each ingress compiles its own per-replica dispatch edges,
    # each edge admits one in-flight execution at a time, so an ingress
    # ceilings at n_replicas/service_time — and a second ingress doubles
    # the edge count and the ceiling. The engine sleeps (simulated
    # accelerator time) instead of burning CPU so that edge ceiling, not
    # the single core, is the measured knee; the offered rate sits past
    # the single-ingress ceiling (~16/0.15 = 107 req/s) while both arms'
    # ceilings stay well under what the shared core can push.
    svc_s = 0.15
    n_rep = 16

    @serve.deployment(name="FDEngine", num_replicas=n_rep,
                      compiled_dispatch=True,
                      ray_actor_options={"num_cpus": 0.1})
    class FDEngine:
        def __call__(self, body):
            time.sleep(svc_s)
            return {"ntokens": body.get("max_tokens", 0)}

    serve.run(FDEngine.bind(), route_prefix="/fd_engine")
    eng_body = {"max_tokens": max_tokens}

    def fd_eng_fleet(count: int) -> list:
        serve.stop_front_door()
        addrs = serve.start_front_door(count=count)
        eng_urls = [f"http://{h}:{p}/fd_engine" for h, p in addrs]
        for u in eng_urls:  # compile this ingress's per-replica edges
            for _ in range(int(n_rep * 1.5)):
                _fire_raw(u, eng_body)
        return eng_urls

    # interleaved A/B (1, 2, 1, 2, ...): box drift hits both arms equally
    per_arm: dict = {1: [], 2: []}
    for rnd in range(ab_rounds):
        for count in (1, 2):
            ab_urls = fd_eng_fleet(count)
            records, wall = _open_loop(
                _fd_fire_split(ab_urls, eng_body, fire_one=_fire_raw),
                ab_rate, duration_s, seed=61 + rnd, max_workers=192)
            pt = _point(records, wall, ab_rate, slo_ttft_ms, max_tokens)
            per_arm[count].append(pt)
            print(f"  front-door ab round {rnd} x{count}: "
                  f"{pt['tokens_per_s']} tok/s, "
                  f"ttft p50 {pt['ttft_p50_ms']} ms")
    serve.stop_front_door()

    def med(pts: list) -> dict:
        keys = ("tokens_per_s", "goodput_rps", "ttft_p50_ms", "ttft_p99_ms",
                "latency_p50_ms", "latency_p99_ms")
        out = dict(pts[0])
        for k in keys:
            out[k] = round(statistics.median(p[k] for p in pts), 2)
        out["completed"] = sum(p["completed"] for p in pts)
        out["errors"] = sum(p["errors"] for p in pts)
        out["offered"] = sum(p["offered"] for p in pts)
        out["wall_s"] = round(sum(p["wall_s"] for p in pts), 3)
        return out

    one, two = med(per_arm[1]), med(per_arm[2])
    ratio = round(two["tokens_per_s"] / one["tokens_per_s"], 2) \
        if one["tokens_per_s"] else 0.0
    return {
        "n_ingress": n_ingress,
        "sweep": points,
        "ab": {"rate_rps": ab_rate, "rounds": ab_rounds,
               "workload": "accelerator-sleep engine (single-core box: "
                           "the knee is per-ingress compiled-edge "
                           "capacity, not CPU parallelism)",
               "engine": {"replicas": n_rep, "service_s": svc_s,
                          "per_ingress_ceiling_rps": round(n_rep / svc_s)},
               "one_ingress": one, "two_ingress": two,
               "tokens_per_s_ratio": ratio},
    }


# ------------------------------------------------------------------ PD A/B
def _fire_pd(url: str, body: dict, timeout: float = 120.0,
             sched_t: float | None = None) -> dict:
    """One PD request over the JSON surface; TTFT is the server-reported
    prefill time (identical definition on both arms of the A/B), while
    latency clocks from the scheduled arrival when given (queue wait
    under overload stays visible)."""
    t0 = time.perf_counter() if sched_t is None else sched_t
    try:
        out = _post(url, body, timeout=timeout)
        res = out.get("result", out)
        return {"ok": True,
                "ttft_ms": res["timings"]["ttft_s"] * 1000.0,
                "latency_ms": (time.perf_counter() - t0) * 1000.0}
    except Exception:
        return {"ok": False}


def run_pd_ab(base: str, rate_rps: float, duration_s: float, rounds: int,
              slo_ttft_ms: float, max_tokens: int) -> dict:
    """Interleaved A/B: co-located PDServer vs disaggregated
    prefill/decode on the same box, same offered load, alternating rounds
    (co, dis, co, dis ...) so box drift hits both arms equally."""
    from ray_tpu import serve
    from ray_tpu.models import llama
    from ray_tpu.serve.llm_paged import PagedLLMConfig
    from ray_tpu.serve.pd import build_pd_deployment, deploy_pd_app

    cfg = PagedLLMConfig(model_config=llama.LlamaConfig.tiny(),
                         max_batch_size=8, max_seq_len=128, block_size=16)
    serve.run(build_pd_deployment(cfg), route_prefix="/pd_co")
    deploy_pd_app(cfg, route_prefix="/pd_dis")
    # shared 32-token system prefix + unique tail: exercises the prefix
    # cache/affinity machinery the same way on both arms
    prefix = list(range(7, 39))

    def body(i):
        return {"prompt_ids": prefix + [40 + (i % 100)],
                "max_tokens": max_tokens}

    for route in ("/pd_co", "/pd_dis"):  # warm both arms
        _post(f"{base}{route}", body(0))

    arms = {"colocated": "/pd_co", "disaggregated": "/pd_dis"}
    per_round: dict = {a: [] for a in arms}
    for rnd in range(rounds):
        for arm, route in arms.items():
            n = {"i": 0}
            n_lock = threading.Lock()

            def fire(sched, route=route, n=n, n_lock=n_lock):
                with n_lock:
                    n["i"] += 1
                    i = n["i"]
                return _fire_pd(f"{base}{route}", body(i), sched_t=sched)

            from ray_tpu.serve import anatomy

            since = anatomy.now_wall()
            records, wall = _open_loop(fire, rate_rps, duration_s,
                                       seed=29 + rnd)
            pt = _point(records, wall, rate_rps, slo_ttft_ms, max_tokens)
            pt["phase_breakdown"] = _phase_breakdown_since(since)
            per_round[arm].append(pt)
            print(f"  pd round {rnd} {arm}: {pt['tokens_per_s']} tok/s, "
                  f"ttft p50 {pt['ttft_p50_ms']} ms, "
                  f"goodput {pt['goodput_rps']}/s")

    def median_point(pts: list) -> dict:
        keys = ("tokens_per_s", "goodput_rps", "ttft_p50_ms", "ttft_p99_ms",
                "latency_p50_ms", "latency_p99_ms")
        out = dict(pts[0])
        out.pop("phase_breakdown", None)  # per-round tables keep theirs
        for k in keys:
            out[k] = round(statistics.median(p[k] for p in pts), 2)
        out["completed"] = sum(p["completed"] for p in pts)
        out["errors"] = sum(p["errors"] for p in pts)
        out["offered"] = sum(p["offered"] for p in pts)
        # counts are summed across rounds, so wall must be too — anyone
        # recomputing completed/wall_s from the artifact should land near
        # the rate columns, not 2x off
        out["wall_s"] = round(sum(p["wall_s"] for p in pts), 3)
        return out

    return {
        "rate_rps": rate_rps, "duration_s": duration_s, "rounds": rounds,
        "max_tokens": max_tokens, "model": "llama-tiny-cpu",
        "colocated": median_point(per_round["colocated"]),
        "disaggregated": median_point(per_round["disaggregated"]),
        "per_round": per_round,
    }


# ----------------------------------------------------------------------- main
def run(rates: list, duration_s: float, slo_ttft_ms: float, max_tokens: int,
        pd_rate: float, pd_rounds: int, pd_max_tokens: int,
        fd: bool = False, fd_ingresses: int = 2, fd_rate: float = 220.0,
        fd_rounds: int = 2) -> dict:
    import ray_tpu

    from ray_tpu import serve

    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    base = f"http://127.0.0.1:{PORT}"
    print(f"ingress sweep: rates {rates} req/s x {duration_s}s, "
          f"SLO ttft<={slo_ttft_ms}ms")
    sweep = run_ingress_sweep(base, rates, duration_s, slo_ttft_ms,
                              max_tokens)
    front_door = None
    if fd:
        print(f"front door: x{fd_ingresses} ingress sweep + "
              f"1-vs-2 A/B at {fd_rate} req/s x {fd_rounds} rounds")
        front_door = run_front_door(rates, duration_s, slo_ttft_ms,
                                    max_tokens, fd_ingresses, fd_rate,
                                    fd_rounds)
    print(f"PD A/B: {pd_rate} req/s x {duration_s}s x {pd_rounds} rounds")
    pd_ab = run_pd_ab(base, rate_rps=pd_rate, duration_s=duration_s,
                      rounds=pd_rounds, slo_ttft_ms=slo_ttft_ms,
                      max_tokens=pd_max_tokens)
    result = {
        "bench": "serve_openai_ingress_sweep",
        "model": "cpu-byte-fallback",
        "max_tokens": max_tokens,
        "slo_ttft_ms": slo_ttft_ms,
        "duration_s": duration_s,
        "ttft_definition": "client-side first SSE data frame (sweep); "
                           "server-reported prefill time (pd_ab)",
        "sweep": sweep,
        "pd_ab": pd_ab,
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if front_door is not None:
        result["front_door"] = front_door
    serve.shutdown()
    ray_tpu.shutdown()
    return result


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--rates", default="2,8,16,32",
                        help="offered arrival rates (req/s), comma-separated")
    parser.add_argument("--duration", type=float, default=8.0,
                        help="seconds of offered load per rate point")
    parser.add_argument("--slo-ttft-ms", type=float, default=250.0)
    parser.add_argument("--max-tokens", type=int, default=8)
    parser.add_argument("--pd-rate", type=float, default=4.0,
                        help="offered rate for the PD A/B rounds")
    parser.add_argument("--pd-rounds", type=int, default=2,
                        help="interleaved rounds per PD arm")
    parser.add_argument("--pd-max-tokens", type=int, default=16,
                        help="decode length for the PD A/B (recorded in "
                             "pd_ab.max_tokens; the top-level max_tokens "
                             "is the ingress sweep's)")
    parser.add_argument("--ingress-per-node", action="store_true",
                        help="front-door arm: replicated-ingress sweep "
                             "(per-ingress arrival split, merged table) + "
                             "interleaved 1-vs-2-ingress A/B")
    parser.add_argument("--fd-ingresses", type=int, default=2,
                        help="fleet size for the front-door sweep")
    parser.add_argument("--fd-rate", type=float, default=220.0,
                        help="offered rate for the 1-vs-2-ingress A/B "
                             "(past the single-ingress dispatch ceiling, "
                             "~107 req/s with the sleep engine)")
    parser.add_argument("--fd-rounds", type=int, default=2,
                        help="interleaved rounds per front-door arm")
    parser.add_argument("--quick", action="store_true",
                        help="smoke sizes (CI)")
    parser.add_argument("--out", default=os.path.join(REPO, "SERVE_BENCH.json"))
    args = parser.parse_args()
    rates = [float(r) for r in args.rates.split(",") if r]
    if args.quick:
        rates, args.duration, args.pd_rounds = [2.0, 8.0], 4.0, 1
        args.fd_rounds = 1
    result = run(rates, args.duration, args.slo_ttft_ms, args.max_tokens,
                 args.pd_rate, args.pd_rounds, args.pd_max_tokens,
                 fd=args.ingress_per_node, fd_ingresses=args.fd_ingresses,
                 fd_rate=args.fd_rate, fd_rounds=args.fd_rounds)
    print(json.dumps({k: v for k, v in result.items() if k != "pd_ab"},
                     indent=2))
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
