#!/usr/bin/env python
"""Wire-schema lint — THIN SHIM over ``ray_tpu.devtools.lint`` (graftlint).

Every check this script used to implement inline now lives in the
pluggable analyzer as a named rule (see ``ray_tpu/devtools/lint/rules/``
and README "Static analysis"):

    check_registry                -> schema-baseline
    check_handlers_have_schemas   -> handlers-schemad
    check_no_pickle_in_rpc        -> no-pickle-in-rpc
    check_blob_zero_copy          -> blob-zero-copy
    check_dag_loop_steady_state   -> dag-loop-rpc-free
    check_elastic_ops             -> version-gating (elastic ops)
    check_profiler_op             -> version-gating (profiler op)
    check_hot_path_instruments    -> hot-path-purity (exec loop + BLOB)
    check_kv_transport            -> version-gating + hot-path-purity
    check_data_streaming_hot_path -> hot-path-purity (data plane)
    check_phase_stamp_hot_path    -> hot-path-purity (timeline)

The function names, list-of-strings returns, stderr format, and exit
codes are preserved so existing imports (tests/test_rpc_wire.py) keep
passing unchanged. Prefer ``python -m ray_tpu.devtools.lint`` for new
work — it also runs the concurrency/invariant pass this shim predates.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ray_tpu.devtools.lint.rules import hotpath as _hotpath  # noqa: E402
from ray_tpu.devtools.lint.rules import wire as _wire  # noqa: E402

# Re-exported: the frozen registries live with the rules now.
SCHEMA_BASELINE = _wire.SCHEMA_BASELINE
HANDLER_FILES = _wire.HANDLER_FILES
PICKLE_ALLOWED = _wire.PICKLE_ALLOWED


def _ctx():
    return _wire.OnDemandCtx(REPO)


def _msgs(findings) -> list:
    return [f"{f.path}:{f.line}: {f.message}" if f.line
            else f"{f.path}: {f.message}" for f in findings]


def _fail(errors: list) -> None:
    for e in errors:
        print(f"SCHEMA LINT: {e}", file=sys.stderr)
    raise SystemExit(1)


def check_registry() -> list:
    return _msgs(_wire.schema_registry_findings(_ctx()))


def check_handlers_have_schemas() -> list:
    return _msgs(_wire.handler_schema_findings(_ctx()))


def check_no_pickle_in_rpc() -> list:
    ctx = _ctx()
    out = []
    rpc_dir = os.path.join(REPO, "ray_tpu", "core", "rpc")
    rels = [f"ray_tpu/core/rpc/{f}" for f in sorted(os.listdir(rpc_dir))
            if f.endswith(".py")]
    rels.append("ray_tpu/core/wire.py")
    for rel in rels:
        fctx = ctx.get(rel)
        if fctx is not None:
            out.extend(_wire.no_pickle_findings(fctx))
    return _msgs(out)


def check_blob_zero_copy() -> list:
    return _msgs(_wire.blob_zero_copy_findings(_ctx()))


def check_dag_loop_steady_state() -> list:
    return _msgs(_wire.dag_loop_findings(_ctx()))


def check_hot_path_instruments() -> list:
    return _msgs(_hotpath.hot_path_findings(
        _ctx(), files={"ray_tpu/dag/exec_loop.py",
                       "ray_tpu/core/rpc/peer.py",
                       "ray_tpu/core/object_plane.py"}))


def check_elastic_ops() -> list:
    return _msgs(_wire.gate_findings(
        _ctx(), ops={"preempt_notice", "plane_replicate"}))


def check_kv_transport() -> list:
    ctx = _ctx()
    return _msgs(_wire.gate_findings(ctx, ops={"kv_ack"}) +
                 _hotpath.hot_path_findings(
                     ctx, files={"ray_tpu/serve/kv_transport.py"}))


def check_data_streaming_hot_path() -> list:
    return _msgs(_hotpath.hot_path_findings(
        _ctx(), files={"ray_tpu/data/streaming.py",
                       "ray_tpu/data/exchange.py"}))


def check_profiler_op() -> list:
    ctx = _ctx()
    return _msgs(_wire.gate_findings(ctx, ops={"profile_capture"}) +
                 _wire.profiler_piggyback_findings(ctx))


def check_phase_stamp_hot_path() -> list:
    return _msgs(_hotpath.hot_path_findings(
        _ctx(), files={"ray_tpu/util/timeline.py",
                       "ray_tpu/core/process_pool.py"}))


def run_all() -> None:
    errors = check_registry()
    errors += check_handlers_have_schemas()
    errors += check_no_pickle_in_rpc()
    errors += check_blob_zero_copy()
    errors += check_dag_loop_steady_state()
    errors += check_hot_path_instruments()
    errors += check_elastic_ops()
    errors += check_kv_transport()
    errors += check_data_streaming_hot_path()
    errors += check_profiler_op()
    errors += check_phase_stamp_hot_path()
    if errors:
        _fail(errors)
    from ray_tpu.core.rpc import schema

    print(f"wire schemas OK: {len(schema.REGISTRY)} ops, "
          f"version {schema.WIRE_VERSION_MIN}..{schema.WIRE_VERSION}, "
          f"baseline {len(SCHEMA_BASELINE)} frozen")


if __name__ == "__main__":
    run_all()
