#!/usr/bin/env python
"""Wire-schema lint: the control plane's append-only contract, enforced.

Runnable standalone (``python scripts/check_wire_schemas.py``) and as a
test (tests/test_round5_fixes-style import; see test_rpc_wire.py). Asserts:

1. every handler registered on a control-plane server (core/cluster.py,
   core/node_agent.py, core/object_plane.py) has a schema entry;
2. schema numbers are unique and APPEND-ONLY against the frozen baseline
   below — renumbering or reusing a shipped number is a wire break;
3. no ``pickle.dumps``/``pickle.loads`` of control structures remains in
   ``core/rpc/`` (the single sanctioned pickle site is userblob.py, the
   opaque user-payload codec) nor in ``core/wire.py``;
4. the raw BLOB frame keeps its zero-copy contract: the ``obj_chunk_raw``
   header schema is registered and version-gated (since>=3, so v2 peers
   never see a frame kind they can't decode), and no payload bytes pass
   through the msgpack packer — or a ``bytes()`` copy — on the plane
   chunk path (codec.blob_header packs lengths only; peer send is
   sendmsg-by-reference, receive is recv_into);
5. the compiled-graph steady-state contract: the actor-side exec loop
   (``ray_tpu/dag/exec_loop.py``) makes NO control-plane calls — no
   ``.remote()``, no rpc ``call``/``notify``, no task submission, no rpc
   imports — channels only; and the ``dag_*`` ops are version-gated
   (since>=4) so an old-wire peer negotiates down to RPC dispatch instead
   of receiving frames it cannot decode.

When you ADD an op: give it the next free number, bump WIRE_VERSION if the
op must be gated, run this lint, then extend the baseline in the same PR.
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Frozen at ISSUE-2 (wire v2). Append new ops; NEVER edit existing pairs.
SCHEMA_BASELINE = {
    "hello": 1, "register_node": 2, "heartbeat": 3, "ref_add": 4,
    "ref_drop": 5, "debug_register": 6, "debug_unregister": 7,
    "debug_list": 8, "locate_object": 9, "object_added": 10,
    "object_removed": 11, "pubsub_publish": 12, "pubsub_subscribe": 13,
    "pubsub_unsubscribe": 14, "pubsub_msg": 15, "client_submit": 16,
    "client_get": 17, "client_put": 18, "client_put_alloc": 19,
    "client_put_seal": 20, "client_wait": 21, "client_free": 22,
    "client_cancel": 23, "client_create_actor": 24, "client_actor_call": 25,
    "client_get_actor": 26, "client_kill_actor": 27, "client_actor_cls": 28,
    "client_next_stream": 29, "client_stream_done": 30, "execute_task": 31,
    "task_blocked": 32, "plane_free": 33, "kill_worker": 34, "num_alive": 35,
    "ping": 36, "shutdown": 37, "obj_meta": 38, "obj_chunk": 39,
    "obj_done": 40, "xl_call": 41, "xl_submit": 42, "xl_get": 43,
    "xl_put": 44, "xl_free": 45, "xl_actor_create": 46, "xl_actor_call": 47,
    "xl_kill_actor": 48, "xl_list_funcs": 49, "kv_get": 50,
    # ISSUE-5 (wire v3): bulk data plane
    "obj_chunk_raw": 51,
    # ISSUE-7 (wire v4): compiled actor graphs
    "dag_install": 52, "dag_teardown": 53, "dag_ch_write": 54,
    "dag_ch_read": 55,
    # ISSUE-8 (wire v5): cluster telemetry plane
    "metrics_push": 56,
    # ISSUE-10 (wire v6): elastic gangs — preemption notices + checkpoint
    # shard replication
    "preempt_notice": 57, "plane_replicate": 58,
    # ISSUE-11 (wire v7): disaggregated PD serving — KV handoff ack
    "kv_ack": 59,
    # ISSUE-13 (wire v8): out-of-band worker profiler (agent-driven SIGUSR
    # stack sampler, artifact sealed to the object plane)
    "profile_capture": 60,
}

# Files whose handler tables must be fully schema'd.
HANDLER_FILES = [
    "ray_tpu/core/cluster.py",
    "ray_tpu/core/node_agent.py",
    "ray_tpu/core/object_plane.py",
    "ray_tpu/core/client_runtime.py",
    "ray_tpu/serve/kv_transport.py",
]

# The sanctioned opaque-payload pickle site inside core/rpc/.
PICKLE_ALLOWED = {"userblob.py"}


def _fail(errors: list) -> None:
    for e in errors:
        print(f"SCHEMA LINT: {e}", file=sys.stderr)
    raise SystemExit(1)


def check_registry() -> list:
    from ray_tpu.core.rpc import schema

    errors = []
    nums: dict = {}
    for name, spec in schema.REGISTRY.items():
        if spec.num in nums:
            errors.append(
                f"op number {spec.num} used by both {name!r} and "
                f"{nums[spec.num]!r}")
        nums[spec.num] = name
        if not (1 <= spec.since <= schema.WIRE_VERSION):
            errors.append(f"op {name!r}: since={spec.since} outside "
                          f"[1, WIRE_VERSION={schema.WIRE_VERSION}]")
    # append-only vs the frozen baseline
    for name, num in SCHEMA_BASELINE.items():
        spec = schema.REGISTRY.get(name)
        if spec is None:
            errors.append(f"baseline op {name!r} (#{num}) was REMOVED — "
                          "shipped ops must stay registered")
        elif spec.num != num:
            errors.append(f"op {name!r} renumbered {num} -> {spec.num} — "
                          "numbers are append-only")
    floor = max(SCHEMA_BASELINE.values())
    for name, spec in schema.REGISTRY.items():
        if name not in SCHEMA_BASELINE and spec.num <= floor:
            errors.append(
                f"new op {name!r} took number {spec.num} <= baseline max "
                f"{floor} — new ops must append (and extend the baseline)")
    return errors


def _string_keys_of_dicts(tree: ast.AST) -> set:
    """All string keys of dict literals + string first-args of handler-map
    subscripts — a superset of op names used as handler-table keys."""
    keys = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
    return keys


_NON_OPS = {
    # dict-literal keys in those files that are not handler-table entries
    "CPU", "TPU", "ok", "node_id", "shm_name", "shm_size", "log_dir",
    "size", "actors", "funcs", "ref", "actor", "__bytes__", "pid", "ts",
    "load1", "mem_total_mb", "mem_available_mb", "agent_rss_mb",
    "workers_alive", "store_used_mb", "store_cap_mb", "wall_ts",
    "num_returns",
    "max_retries", "retry_exceptions", "name", "resources", "runtime_env",
    "isolate_process", "peer_hello", "input_chans", "output_chan",
    "_trace_ctx",
    # kv_transport.py descriptor/stats fields (not handler-table keys)
    "live_handoffs", "live_bytes", "k_shape", "v_shape", "local_pulls",
}


def check_handlers_have_schemas() -> list:
    """Every ``"op": handler`` table entry and every peer.call/notify op
    literal in the control-plane modules must name a registered schema."""
    from ray_tpu.core.rpc import schema

    errors = []
    for rel in HANDLER_FILES:
        path = os.path.join(REPO, rel)
        tree = ast.parse(open(path).read(), filename=rel)
        # call sites: peer.call("op", ...) / notify / call_async
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("call", "call_async", "notify")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                op = node.args[0].value
                if op not in schema.REGISTRY:
                    errors.append(f"{rel}: call site uses op {op!r} with no "
                                  "schema entry")
        # handler tables: dict literals whose values are function refs and
        # whose keys look like op names
        for key in _string_keys_of_dicts(tree):
            if key in _NON_OPS or not key.replace("_", "").isalpha():
                continue
            if key.islower() and "_" in key and key not in schema.REGISTRY:
                # plausible op-shaped key with no schema — flag it
                errors.append(f"{rel}: dict key {key!r} looks like an op "
                              "but has no schema entry (add one, or list "
                              "it in _NON_OPS)")
    return errors


def check_no_pickle_in_rpc() -> list:
    errors = []
    rpc_dir = os.path.join(REPO, "ray_tpu", "core", "rpc")
    for fname in sorted(os.listdir(rpc_dir)):
        if not fname.endswith(".py") or fname in PICKLE_ALLOWED:
            continue
        src = open(os.path.join(rpc_dir, fname)).read()
        tree = ast.parse(src, filename=fname)
        for node in ast.walk(tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                names = [a.name for a in node.names]
                mod = getattr(node, "module", "") or ""
                if "pickle" in names or "cloudpickle" in names or \
                        mod in ("pickle", "cloudpickle"):
                    errors.append(
                        f"core/rpc/{fname}:{node.lineno}: imports pickle — "
                        "control-plane frames must stay msgpack-native "
                        "(opaque payloads go through userblob.py)")
            if (isinstance(node, ast.Attribute)
                    and node.attr in ("dumps", "loads")
                    and isinstance(node.value, ast.Name)
                    and node.value.id in ("pickle", "cloudpickle")):
                errors.append(
                    f"core/rpc/{fname}:{node.lineno}: "
                    f"{node.value.id}.{node.attr} of a control structure")
    # the legacy shim must carry no pickling either (AST check: prose in the
    # docstring may mention the history)
    wire_path = os.path.join(REPO, "ray_tpu", "core", "wire.py")
    wire_tree = ast.parse(open(wire_path).read(), filename="wire.py")
    for node in ast.walk(wire_tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            names = [a.name for a in node.names]
            mod = getattr(node, "module", "") or ""
            if "pickle" in names or "cloudpickle" in names or \
                    mod in ("pickle", "cloudpickle"):
                errors.append(f"core/wire.py:{node.lineno}: imports pickle — "
                              "the shim must stay transport-free")
    return errors


def _calls_in(fn: ast.FunctionDef, names: set) -> list:
    """(lineno, name) for every call inside ``fn`` whose callee name/attr is
    in ``names`` (matches both ``packb(...)`` and ``msgpack.packb(...)``)."""
    hits = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        callee = node.func
        name = (callee.attr if isinstance(callee, ast.Attribute)
                else callee.id if isinstance(callee, ast.Name) else None)
        if name in names:
            hits.append((node.lineno, name))
    return hits


def _find_funcs(tree: ast.AST, wanted: set) -> dict:
    return {node.name: node for node in ast.walk(tree)
            if isinstance(node, ast.FunctionDef) and node.name in wanted}


def check_blob_zero_copy() -> list:
    """The v3 BLOB contract: raw kind version-gated, header schema frozen,
    payload bytes never packed, joined, or copied on the chunk path."""
    from ray_tpu.core.rpc import codec, schema

    errors = []
    # version gate: obj_chunk_raw (the only BLOB-replied op) must be >= v3
    spec = schema.REGISTRY.get("obj_chunk_raw")
    if spec is None:
        errors.append("obj_chunk_raw (the BLOB header schema) is not "
                      "registered")
    elif spec.since < 3:
        errors.append(f"obj_chunk_raw gated since={spec.since} < 3 — a v2 "
                      "peer would receive a frame kind it cannot decode")
    if getattr(codec, "BLOB", None) is None or codec.BLOB <= codec.GOODBYE:
        errors.append("codec.BLOB must be a NEW frame kind appended after "
                      "GOODBYE (old decoders reject unknown kinds cleanly)")
    # the packer sees header fields only: blob_header takes lengths, never
    # the payload
    import inspect

    params = list(inspect.signature(codec.blob_header).parameters)
    if params != ["reply_to", "payload_len"]:
        errors.append(f"codec.blob_header{tuple(params)} — must take "
                      "(reply_to, payload_len): payload bytes never enter "
                      "the msgpack packer")
    # peer: sendmsg-by-reference out, recv_into in — no packer, no copies
    peer_path = os.path.join(REPO, "ray_tpu", "core", "rpc", "peer.py")
    peer_fns = _find_funcs(ast.parse(open(peer_path).read(), "peer.py"),
                           {"_send_blob", "_read_blob"})
    packers = {"pack", "packb", "dumps", "reply_frame"}
    for name in ("_send_blob", "_read_blob"):
        fn = peer_fns.get(name)
        if fn is None:
            errors.append(f"peer.py: {name} missing — BLOB path gone?")
            continue
        for lineno, callee in _calls_in(fn, packers):
            errors.append(f"peer.py:{lineno}: {name} calls {callee}() — "
                          "BLOB payloads must bypass the msgpack packer")
    if "_send_blob" in peer_fns and not _calls_in(peer_fns["_send_blob"],
                                                  {"sendmsg"}):
        errors.append("peer.py: _send_blob no longer scatter-gathers via "
                      "sendmsg (header+payload in one syscall, by reference)")
    if "_read_blob" in peer_fns:
        if _calls_in(peer_fns["_read_blob"], {"_recv_exact"}):
            errors.append("peer.py: _read_blob uses copying _recv_exact — "
                          "payload must land via recv_into")
        if not _calls_in(peer_fns["_read_blob"], {"_recv_exact_into"}):
            errors.append("peer.py: _read_blob must receive via "
                          "_recv_exact_into (recv_into, zero-copy)")
    # plane: the raw-chunk handler serves a store view, never a bytes() copy
    plane_path = os.path.join(REPO, "ray_tpu", "core", "object_plane.py")
    plane_fns = _find_funcs(ast.parse(open(plane_path).read(),
                                      "object_plane.py"), {"_h_chunk_raw"})
    fn = plane_fns.get("_h_chunk_raw")
    if fn is None:
        errors.append("object_plane.py: _h_chunk_raw handler missing")
    else:
        for lineno, callee in _calls_in(fn, packers | {"bytes", "bytearray"}):
            errors.append(f"object_plane.py:{lineno}: _h_chunk_raw calls "
                          f"{callee}() — raw chunks must leave as views "
                          "into the store mapping (RawReply)")
        if not _calls_in(fn, {"RawReply"}):
            errors.append("object_plane.py: _h_chunk_raw must answer with "
                          "a RawReply (raw BLOB frame)")
    return errors


# Control-plane call names that must never appear in the compiled-graph
# exec loop: steady state is channels only (ISSUE-7 acceptance).
_DAG_LOOP_FORBIDDEN_CALLS = {
    "remote", "call", "call_async", "notify", "submit_task",
    "submit_actor_task", "create_actor",
}
_DAG_LOOP_FORBIDDEN_IMPORTS = (
    "ray_tpu.core.rpc", "ray_tpu.core.runtime", "ray_tpu.core.cluster",
    "ray_tpu.core.client_runtime", "ray_tpu.core.api",
)


def check_dag_loop_steady_state() -> list:
    """The resident exec loop a compiled graph installs in each actor makes
    zero control-plane calls at steady state — its module may touch shm
    channels and the serializer, nothing else."""
    errors = []
    path = os.path.join(REPO, "ray_tpu", "dag", "exec_loop.py")
    if not os.path.exists(path):
        return ["ray_tpu/dag/exec_loop.py missing — compiled-graph loop gone?"]
    tree = ast.parse(open(path).read(), filename="exec_loop.py")
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            callee = node.func
            name = (callee.attr if isinstance(callee, ast.Attribute)
                    else callee.id if isinstance(callee, ast.Name) else None)
            if name in _DAG_LOOP_FORBIDDEN_CALLS:
                errors.append(
                    f"dag/exec_loop.py:{node.lineno}: calls {name}() — the "
                    "compiled-graph loop must be channels-only at steady "
                    "state (no RPC, no task submission)")
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            mods = [a.name for a in node.names]
            mods.append(getattr(node, "module", "") or "")
            for m in mods:
                if any(m == f or m.startswith(f + ".")
                       for f in _DAG_LOOP_FORBIDDEN_IMPORTS):
                    errors.append(
                        f"dag/exec_loop.py:{node.lineno}: imports {m} — the "
                        "loop module must not link the control plane")
    # run_plan must exist and speak the channel surface
    fns = _find_funcs(tree, {"run_plan"})
    if "run_plan" not in fns:
        errors.append("dag/exec_loop.py: run_plan missing")
    elif not _calls_in(fns["run_plan"], {"read_view", "read", "write"}):
        errors.append("dag/exec_loop.py: run_plan no longer moves data over "
                      "channel read/write")
    # version gating: dag ops must be >= v4 so old peers negotiate down
    from ray_tpu.core.rpc import schema

    for op in ("dag_install", "dag_teardown", "dag_ch_write", "dag_ch_read"):
        spec = schema.REGISTRY.get(op)
        if spec is None:
            errors.append(f"{op} schema missing")
        elif spec.since < 4:
            errors.append(f"{op} gated since={spec.since} < 4 — an old-wire "
                          "peer must fall back to RPC dispatch, not receive "
                          "undecodable frames")
    return errors


# Metric construction / registry-touching call names that must never run
# per-event on a hot path — instruments bind at import/install time
# (util/metrics.py bind contract, ISSUE-8 telemetry plane).
_METRIC_CONSTRUCT_CALLS = {
    "Counter", "Gauge", "Histogram", "bind", "get_metric",
    "registry_snapshot", "wire_snapshot", "prometheus_text",
    "attach_producer",
}
# Any metric recording at all is banned inside the raw BLOB frame paths —
# a lock per frame there is a measured regression (pull metrics live at
# whole-pull granularity in object_plane instead).
_METRIC_RECORD_CALLS = {"inc", "observe", "record"}


def check_hot_path_instruments() -> list:
    """Hot-path telemetry contract: ``dag/exec_loop.py`` binds its
    instruments at module import (and never constructs/looks one up inside
    a function), and the BLOB send/recv frame paths (``peer._send_blob``/
    ``_read_blob``, ``object_plane._h_chunk_raw``) carry NO metric calls at
    all — no per-event registry lookups, no lock-per-frame regressions."""
    errors = []
    # 1) exec_loop: module-level bind exists...
    loop_path = os.path.join(REPO, "ray_tpu", "dag", "exec_loop.py")
    tree = ast.parse(open(loop_path).read(), filename="exec_loop.py")
    top_binds = 0
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            callee = node.value.func
            name = (callee.attr if isinstance(callee, ast.Attribute)
                    else callee.id if isinstance(callee, ast.Name) else None)
            if name == "bind":
                top_binds += 1
    if top_binds == 0:
        errors.append(
            "dag/exec_loop.py: no module-level instrument bind() — hot-loop "
            "metrics must be bound at import time, not per event")
    # ...and no function body constructs instruments / touches the registry
    for fn in ast.walk(tree):
        if not isinstance(fn, ast.FunctionDef):
            continue
        for lineno, callee in _calls_in(fn, _METRIC_CONSTRUCT_CALLS):
            errors.append(
                f"dag/exec_loop.py:{lineno}: {fn.name} calls {callee}() — "
                "instruments bind at import time, never per event")
    # 2) BLOB frame paths: zero metric traffic
    for rel, fnames in (("ray_tpu/core/rpc/peer.py",
                         {"_send_blob", "_read_blob"}),
                        ("ray_tpu/core/object_plane.py", {"_h_chunk_raw"})):
        path = os.path.join(REPO, rel)
        fns = _find_funcs(ast.parse(open(path).read(), rel), fnames)
        for fname in sorted(fnames):
            fn = fns.get(fname)
            if fn is None:
                errors.append(f"{rel}: {fname} missing — BLOB path gone?")
                continue
            banned = _METRIC_CONSTRUCT_CALLS | _METRIC_RECORD_CALLS
            for lineno, callee in _calls_in(fn, banned):
                errors.append(
                    f"{rel}:{lineno}: {fname} calls {callee}() — the raw "
                    "BLOB frame path must stay metric-free (a lock per "
                    "frame is a measured regression; account at pull "
                    "granularity instead)")
    return errors


def check_elastic_ops() -> list:
    """The v6 elastic-gang ops are version-gated: a <v6 agent must never be
    asked to serve ``plane_replicate`` (it has no handler), and a <v6 head
    must never receive ``preempt_notice`` (undecodable op number) — the
    sender checks ``negotiated_version`` before using either."""
    from ray_tpu.core.rpc import schema

    errors = []
    for op in ("preempt_notice", "plane_replicate"):
        spec = schema.REGISTRY.get(op)
        if spec is None:
            errors.append(f"{op} schema missing — elastic gang wire gone?")
        elif spec.since < 6:
            errors.append(f"{op} gated since={spec.since} < 6 — an old-wire "
                          "peer would receive an op it cannot serve/decode")
    spec = schema.REGISTRY.get("plane_replicate")
    if spec is not None and not spec.blocking:
        errors.append("plane_replicate must be blocking=True — the agent "
                      "handler parks on a whole-object pull and must not "
                      "occupy a bounded reactor slot")
    return errors


def check_kv_transport() -> list:
    """The v7 KV-transfer contract (ISSUE-11 PD disaggregation):

    - ``kv_ack`` is version-gated (since>=7) — a <v7 holder must never
      receive an op number it cannot decode; the puller skips the ack and
      the publisher's TTL sweep reclaims instead.
    - the handoff hot path (``KVTransport.publish``/``pull``) never
      constructs or looks up a metric — instruments bind at module import
      (the PR-8 hot-path contract; recording through bound handles is
      fine, registry traffic per handoff is not).
    - the pull path stays zero-copy: ``pull`` rides ``pull_into`` (BLOB
      frames recv_into the local store slot), with the bytes-returning
      ``pull`` only as the store-less fallback.
    """
    from ray_tpu.core.rpc import schema

    errors = []
    spec = schema.REGISTRY.get("kv_ack")
    if spec is None:
        errors.append("kv_ack schema missing — KV handoff ack gone?")
    elif spec.since < 7:
        errors.append(f"kv_ack gated since={spec.since} < 7 — an old-wire "
                      "holder would receive an op it cannot decode")
    path = os.path.join(REPO, "ray_tpu", "serve", "kv_transport.py")
    if not os.path.exists(path):
        return errors + ["ray_tpu/serve/kv_transport.py missing"]
    tree = ast.parse(open(path).read(), filename="kv_transport.py")
    fns = _find_funcs(tree, {"publish", "pull"})
    for name in ("publish", "pull"):
        fn = fns.get(name)
        if fn is None:
            errors.append(f"kv_transport.py: {name} missing — handoff "
                          "path gone?")
            continue
        for lineno, callee in _calls_in(fn, _METRIC_CONSTRUCT_CALLS):
            errors.append(
                f"kv_transport.py:{lineno}: {name} calls {callee}() — the "
                "handoff hot path must stay metric-construction-free "
                "(bind instruments at import, record through the handles)")
    if "pull" in fns and not _calls_in(fns["pull"],
                                       {"pull_into", "pull_into_or_pull"}):
        errors.append("kv_transport.py: pull no longer rides pull_into — "
                      "KV pages must land zero-copy in the local store")
    return errors


# Streaming-data-plane hot functions (ISSUE-12): the operator pump and the
# consumer-side fetch/prefetch loops. They may submit tasks and get objects
# through the public ray_tpu API (which owns retry/failover), but must not
# speak the wire directly nor construct/look up metrics per block —
# instruments bind at operator-install time (the exec-loop/kv-transport
# contract, applied to the data plane).
_DATA_HOT_FUNCS = {
    "ray_tpu/data/streaming.py": {
        "_drive_op", "fetch_block", "_prefetch_pump", "__next__",
        "_transform_to_plane", "_slice_to_plane",
    },
    "ray_tpu/data/exchange.py": {
        "_reduce_partition", "_map_partition", "_pull_slices",
    },
}
_DATA_HOT_FORBIDDEN_RPC = {"call", "call_async", "notify"}


def check_data_streaming_hot_path() -> list:
    """The ISSUE-12 streaming hot path: pump/pull loops are
    metric-bind()-only (no instrument construction or registry lookups per
    block) and RPC-free (no direct wire calls — data moves via tasks +
    plane pulls), and the data modules never import the wire layer."""
    errors = []
    for rel, fnames in sorted(_DATA_HOT_FUNCS.items()):
        path = os.path.join(REPO, rel)
        if not os.path.exists(path):
            errors.append(f"{rel} missing — streaming data plane gone?")
            continue
        tree = ast.parse(open(path).read(), filename=rel)
        # module must not link the control-plane wire directly
        for node in ast.walk(tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                mods = [a.name for a in node.names]
                mods.append(getattr(node, "module", "") or "")
                for m in mods:
                    if m == "ray_tpu.core.rpc" or \
                            m.startswith("ray_tpu.core.rpc."):
                        errors.append(
                            f"{rel}:{node.lineno}: imports {m} — the data "
                            "plane rides tasks + plane pulls, never the "
                            "wire directly")
        fns = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef) and node.name in fnames:
                fns.setdefault(node.name, node)
        for fname in sorted(fnames):
            fn = fns.get(fname)
            if fn is None:
                errors.append(f"{rel}: hot function {fname} missing — "
                              "streaming pump/pull loop renamed? (update "
                              "_DATA_HOT_FUNCS)")
                continue
            for lineno, callee in _calls_in(fn, _METRIC_CONSTRUCT_CALLS):
                errors.append(
                    f"{rel}:{lineno}: {fname} calls {callee}() — streaming "
                    "hot path must record through handles bound at "
                    "operator-install time, never construct/look up "
                    "instruments per block")
            for lineno, callee in _calls_in(fn, _DATA_HOT_FORBIDDEN_RPC):
                errors.append(
                    f"{rel}:{lineno}: {fname} calls {callee}() — streaming "
                    "hot path is RPC-free (tasks and gets go through the "
                    "public API)")
    # the exchange's map stage must seal slices plane-side (put inside the
    # task), and the reduce stage must PULL its own slices (get inside the
    # task) — the ISSUE-12 plane-native contract
    ex_path = os.path.join(REPO, "ray_tpu", "data", "exchange.py")
    if os.path.exists(ex_path):
        ex_fns = _find_funcs(ast.parse(open(ex_path).read(), "exchange.py"),
                             {"_map_partition", "_reduce_partition"})
        if "_map_partition" in ex_fns and \
                not _calls_in(ex_fns["_map_partition"], {"put"}):
            errors.append("exchange.py: _map_partition no longer seals "
                          "slices via ray_tpu.put — slices must stay in "
                          "the mapper's node store")
        if "_reduce_partition" in ex_fns and \
                not _calls_in(ex_fns["_reduce_partition"],
                              {"get", "_pull_slices"}):
            errors.append("exchange.py: _reduce_partition no longer pulls "
                          "its own slices — reducers must resolve slices "
                          "through the plane failover path themselves")
    return errors


def check_profiler_op() -> list:
    """The v8 out-of-band profiler contract: ``profile_capture`` is
    version-gated (since>=8 — a <v8 agent has no handler and must never be
    sent the op; the head checks ``negotiated_version`` first) and
    blocking (the agent-side handler parks for the whole sample window and
    must not occupy a bounded reactor slot)."""
    from ray_tpu.core.rpc import schema

    errors = []
    spec = schema.REGISTRY.get("profile_capture")
    if spec is None:
        return ["profile_capture schema missing — out-of-band profiler "
                "wire gone?"]
    if spec.since < 8:
        errors.append(f"profile_capture gated since={spec.since} < 8 — an "
                      "old-wire agent would receive an op it cannot serve")
    if not spec.blocking:
        errors.append("profile_capture must be blocking=True — the agent "
                      "handler parks for the sample window")
    # the metrics_push piggyback field must exist (the timeline half rides
    # the v5 push; removing the field silently severs worker phase lanes)
    push = schema.REGISTRY.get("metrics_push")
    if push is not None and "phases" not in push.field_map():
        errors.append("metrics_push lost its `phases` field — worker "
                      "timeline entries have no transport")
    return errors


# The worker-side phase-stamping path (ISSUE-13 timeline): the stamp is a
# ring append — it must never construct/look up instruments nor speak the
# wire, exactly like the dag exec loop's sampled metrics.
_PHASE_STAMP_FORBIDDEN = _METRIC_CONSTRUCT_CALLS | {
    "call", "call_async", "notify", "remote", "submit_task",
}


def check_phase_stamp_hot_path() -> list:
    """``util/timeline.py``'s recording half is bind-only: the stamp/record
    functions make no instrument construction/lookup and no RPC, the
    module never links the control plane, and the worker exec path
    (``process_pool._worker_main``) actually stamps phases."""
    errors = []
    tl_path = os.path.join(REPO, "ray_tpu", "util", "timeline.py")
    if not os.path.exists(tl_path):
        return ["ray_tpu/util/timeline.py missing — cluster timeline gone?"]
    tree = ast.parse(open(tl_path).read(), filename="timeline.py")
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            mods = [a.name for a in node.names]
            mods.append(getattr(node, "module", "") or "")
            for m in mods:
                for f in _DAG_LOOP_FORBIDDEN_IMPORTS:
                    if (m == f or m.startswith(f + ".")) \
                            and f != "ray_tpu.core.runtime":
                        errors.append(
                            f"util/timeline.py:{node.lineno}: imports {m} — "
                            "the recording module must not link the wire")
    fns = _find_funcs(tree, {"phase_reply", "stamp_task_phases",
                             "record_span", "drain_since"})
    for name in ("phase_reply", "stamp_task_phases", "record_span",
                 "drain_since"):
        fn = fns.get(name)
        if fn is None:
            errors.append(f"util/timeline.py: {name} missing — phase "
                          "recording path renamed? (update the lint)")
            continue
        for lineno, callee in _calls_in(fn, _PHASE_STAMP_FORBIDDEN):
            errors.append(
                f"util/timeline.py:{lineno}: {name} calls {callee}() — the "
                "phase-stamping path is bind-only (ring append under one "
                "lock; no instruments, no RPC)")
    # export() may import the runtime (head-side merge), but the recording
    # functions above may not — and both halves of the stamping path must
    # stay wired: the worker exec path ships clocks on the done reply, the
    # pool parent (head driver / node agent — the pushing processes) stamps
    pp_path = os.path.join(REPO, "ray_tpu", "core", "process_pool.py")
    pp_fns = _find_funcs(ast.parse(open(pp_path).read(), "process_pool.py"),
                         {"_worker_main", "_reply_reader"})
    wm = pp_fns.get("_worker_main")
    if wm is None:
        errors.append("process_pool.py: _worker_main missing")
    elif not _calls_in(wm, {"phase_reply"}):
        errors.append("process_pool.py: _worker_main no longer ships phase "
                      "clocks on the done reply — worker timeline lanes go "
                      "dark")
    rr = pp_fns.get("_reply_reader")
    if rr is None:
        errors.append("process_pool.py: _reply_reader missing")
    elif not _calls_in(rr, {"stamp_task_phases"}):
        errors.append("process_pool.py: _reply_reader no longer stamps "
                      "worker phase clocks into the parent's timeline ring")
    return errors


def run_all() -> None:
    errors = check_registry()
    errors += check_handlers_have_schemas()
    errors += check_no_pickle_in_rpc()
    errors += check_blob_zero_copy()
    errors += check_dag_loop_steady_state()
    errors += check_hot_path_instruments()
    errors += check_elastic_ops()
    errors += check_kv_transport()
    errors += check_data_streaming_hot_path()
    errors += check_profiler_op()
    errors += check_phase_stamp_hot_path()
    if errors:
        _fail(errors)
    from ray_tpu.core.rpc import schema

    print(f"wire schemas OK: {len(schema.REGISTRY)} ops, "
          f"version {schema.WIRE_VERSION_MIN}..{schema.WIRE_VERSION}, "
          f"baseline {len(SCHEMA_BASELINE)} frozen")


if __name__ == "__main__":
    run_all()
