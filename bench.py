"""Benchmark: flagship-model training throughput on the attached accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: tokens/sec/chip for a Llama-style model train step (fwd+bwd+adamw),
bfloat16, remat on — the Ray-Train-equivalent north-star from BASELINE.json.
The model size is auto-picked to fit the attached chip (v5e ~16GB HBM); on CPU
(no chip) a tiny config keeps the harness honest. ``vs_baseline`` is measured
throughput / reference-derived roofline expectation for this chip (40% MFU —
a strong Ray-Train GPU baseline equivalent); >1.0 beats it.
"""

from __future__ import annotations

import json
import os
import sys
import time


def probe_tpu(attempts: int = 3, probe_timeout: float = 120.0, backoff: float = 20.0) -> bool:
    """Check the accelerator is reachable WITHOUT risking this process.

    The TPU tunnel in this environment admits one process and can wedge
    (hang in backend init) after a killed client. Probing from a short-lived
    subprocess means a wedge costs one timeout, not the whole bench; bounded
    retries with backoff ride out a stale holder releasing the chip."""
    import os
    import subprocess

    for i in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                capture_output=True, text=True, timeout=probe_timeout,
                env=dict(os.environ),
            )
            lines = (r.stdout or "").strip().splitlines()
            plat = lines[-1] if lines else ""
            if r.returncode == 0 and plat and plat != "cpu":
                return True
            sys.stderr.write(f"probe {i+1}/{attempts}: platform={plat!r} rc={r.returncode}\n")
        except subprocess.TimeoutExpired:
            sys.stderr.write(f"probe {i+1}/{attempts}: timed out (tunnel wedged?)\n")
        if i < attempts - 1:
            time.sleep(backoff * (i + 1))
    return False


def pick_device():
    """Prefer the attached accelerator; fall back to host CPU.

    Never request platforms by name — probing an unknown plugin name poisons
    jax's backend cache; jax.devices() returns the default (highest-priority)
    platform's devices."""
    import jax

    devs = jax.devices()
    return devs[0], devs[0].platform


def _watchdog(seconds: float):
    """Emit a parseable failure line if backend init wedges (the TPU tunnel admits
    one process at a time; a stale holder can block client creation forever)."""
    import os
    import threading

    done = threading.Event()

    def fire():
        if not done.wait(seconds):
            print(
                json.dumps(
                    {
                        "metric": "train_tokens_per_sec_per_chip_unavailable",
                        "value": 0.0,
                        "unit": "tokens/s/chip",
                        "vs_baseline": 0.0,
                    }
                ),
                flush=True,
            )
            os._exit(3)

    threading.Thread(target=fire, daemon=True).start()
    return done


CACHE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "BENCH_CACHE.json")


def _load_cached_tpu_result():
    """Most recent REAL on-chip measurement (written by a successful TPU run).

    The tunnel in this environment admits one process and can wedge for hours
    after a killed client; when it is wedged at bench time, the honest best
    answer is the measured number from earlier in the same build, clearly
    labeled as cached — not an unrelated CPU number."""
    try:
        with open(CACHE_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _save_cached_tpu_result(result: dict) -> None:
    try:
        with open(CACHE_PATH, "w") as f:
            json.dump(result, f)
    except OSError:
        pass


def main():
    import os

    # Decide CPU vs TPU BEFORE importing jax in this process: if the tunnel
    # probe fails, pin to CPU so the bench still reports a measured number
    # instead of hanging in backend init (round-1 failure mode).
    want_cpu = os.environ.get("RAY_TPU_BENCH_CPU") == "1"
    if not want_cpu and not probe_tpu():
        cached = (None if os.environ.get("RAY_TPU_BENCH_NO_CACHE") == "1"
                  else _load_cached_tpu_result())
        if cached is not None:
            sys.stderr.write(
                "TPU tunnel unreachable after retries; reporting the cached "
                f"on-chip measurement from {cached.get('measured_at')} "
                f"(commit {cached.get('git_commit', '?')}); set "
                "RAY_TPU_BENCH_NO_CACHE=1 to force a live attempt\n")
            print(json.dumps({
                "metric": cached["metric"] + "_cached",
                "value": cached["value"],
                "unit": cached["unit"],
                "vs_baseline": cached["vs_baseline"],
            }))
            return
        sys.stderr.write("TPU unreachable after retries; falling back to CPU bench\n")
        want_cpu = True

    import jax

    if want_cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from ray_tpu.models import llama
    from ray_tpu.train import spmd
    from jax.sharding import Mesh

    import numpy as np

    init_guard = _watchdog(300.0)
    device, platform = pick_device()
    init_guard.set()
    on_chip = platform != "cpu"

    # Candidates tried in order (first that fits/compiles wins). The round-4
    # sweep family (scripts/tpu_sweep.py): bigger hidden sizes raise MFU —
    # larger matmuls amortize better on the MXU and shrink the attention
    # fraction — so the 2048-wide configs lead; the round-2/3 measured
    # config (dots bs8, hidden 1024, 0.83x) remains the known-good fallback.
    base = dict(
        vocab_size=32000, hidden_size=1024, intermediate_size=4096,
        num_layers=16, num_heads=16, num_kv_heads=8, max_seq_len=2048,
        rope_theta=10000.0, dtype=jnp.bfloat16, remat=True,
    )
    big = dict(
        vocab_size=32000, hidden_size=2048, intermediate_size=8192,
        num_layers=12, num_heads=16, num_kv_heads=8, max_seq_len=2048,
        rope_theta=10000.0, dtype=jnp.bfloat16, remat=True,
    )
    if on_chip:
        candidates = [
            (llama.LlamaConfig(**big, remat_policy="dots"), 8, 2048, 20),
            (llama.LlamaConfig(**base, remat_policy="dots"), 8, 2048, 20),
            (llama.LlamaConfig(**base), 8, 2048, 20),
        ]
    else:
        candidates = [(llama.LlamaConfig.tiny(), 2, 64, 3)]

    mesh = Mesh(np.asarray([device]).reshape(1, 1, 1, 1, 1), ("data", "fsdp", "tensor", "seq", "expert"))

    def measure(cfg, batch, seqlen, iters):
        key = jax.random.PRNGKey(0)
        with jax.default_device(device):
            state = spmd.init_state(cfg, key, optimizer=spmd.make_optimizer(warmup=1))
            step = spmd.make_train_step(cfg, mesh)(state)
            tokens = jax.random.randint(key, (batch, seqlen), 0, cfg.vocab_size)
            targets = jax.random.randint(key, (batch, seqlen), 0, cfg.vocab_size)
            # compile + warmup
            state, metrics = step(state, tokens, targets)
            jax.block_until_ready(metrics["loss"])
            t0 = time.perf_counter()
            for _ in range(iters):
                state, metrics = step(state, tokens, targets)
            jax.block_until_ready(metrics["loss"])
            dt = time.perf_counter() - t0
        return batch * seqlen * iters / dt

    tokens_per_sec = None
    for i, (cfg, batch, seqlen, iters) in enumerate(candidates):
        try:
            tokens_per_sec = measure(cfg, batch, seqlen, iters)
            break
        except Exception as e:  # noqa: BLE001 — OOM/compile: next candidate
            if i == len(candidates) - 1:
                raise
            sys.stderr.write(
                f"candidate {i} ({cfg.remat_policy} remat) failed "
                f"({type(e).__name__}); trying the fallback config\n")
            import gc

            gc.collect()

    # Roofline expectation: 40% MFU on this chip's peak bf16 FLOPs.
    peak_flops = {"tpu": 197e12, "axon": 197e12}.get(platform, 1e11)  # v5e ~197 TFLOPs bf16
    n_params = llama.param_count_analytic(cfg)
    step_flops_per_token = 6 * n_params  # fwd+bwd
    expected_tps = 0.40 * peak_flops / step_flops_per_token
    vs_baseline = tokens_per_sec / expected_tps

    result = {
        "metric": f"train_tokens_per_sec_per_chip_{platform}",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs_baseline, 4),
    }
    if on_chip:
        stamp = {"measured_at": time.strftime("%Y-%m-%dT%H:%M:%S")}
        try:
            import subprocess

            stamp["git_commit"] = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=10,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            ).stdout.strip()
        except Exception:
            pass
        _save_cached_tpu_result({**result, **stamp})
    print(json.dumps(result))


if __name__ == "__main__":
    try:
        main()
    except BaseException as e:  # noqa: BLE001 - the driver needs ONE parseable line
        if isinstance(e, SystemExit) and not e.code:
            raise  # clean exit (e.g. --help) is not a failure
        import traceback

        traceback.print_exc()
        print(
            json.dumps(
                {
                    "metric": f"train_tokens_per_sec_per_chip_error_{type(e).__name__}",
                    "value": 0.0,
                    "unit": "tokens/s/chip",
                    "vs_baseline": 0.0,
                }
            ),
            flush=True,
        )
        raise SystemExit(2)
